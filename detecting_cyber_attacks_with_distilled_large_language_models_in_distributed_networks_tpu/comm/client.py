"""Federated client session for the cross-host demo-parity mode.

The reference's client session is: connect, gzip-pickle upload on port
12345, poll a second port every 1 s until the server opens it, download
with a retry budget (reference client1.py:276-336). Here the whole
exchange is one request/response on one connection — upload the local
params, block until the aggregated params come back on the same socket —
with connection retry/backoff standing in for the reference's
``wait_for_server`` probe loop (client1.py:298-311) but without the
probe-kills-server race (SURVEY.md §5).
"""

from __future__ import annotations

import socket
import time
from typing import Any, Mapping

from ..utils.logging import get_logger
from . import framing, wire

log = get_logger()


def connect_with_retry(
    host: str,
    port: int,
    *,
    timeout: float = 300.0,
    poll_interval: float = 1.0,  # the reference's 1 s probe cadence
) -> socket.socket:
    """Dial until the server is up or ``timeout`` elapses."""
    deadline = time.monotonic() + timeout
    last: Exception | None = None
    while time.monotonic() < deadline:
        try:
            sock = socket.create_connection(
                (host, port), timeout=max(0.1, deadline - time.monotonic())
            )
            return sock
        except OSError as e:
            last = e
            time.sleep(poll_interval)
    raise ConnectionError(f"server {host}:{port} unreachable after {timeout}s: {last}")


class FederatedClient:
    """One client's view of a federated round over TCP."""

    def __init__(
        self,
        host: str,
        port: int,
        *,
        client_id: int,
        timeout: float = 300.0,  # the reference's TIMEOUT (client1.py:22)
        compression: str = "none",
        auth_key: bytes | None = None,
    ):
        self.host = host
        self.port = port
        self.client_id = client_id
        self.timeout = timeout
        self.compression = compression
        self.auth_key = auth_key

    def exchange(
        self,
        params: Any,
        *,
        n_samples: int = 1,
        meta: Mapping[str, Any] | None = None,
        max_retries: int = 5,  # the reference's retry budget (client1.py:314)
    ) -> dict:
        """Upload local params, return the aggregated params (nested dict).

        Retries the whole round-trip on connection errors; a server-side
        WireError (e.g. CRC mismatch after corruption) also retries with a
        fresh upload.
        """
        base_meta = {
            "client_id": self.client_id,
            "n_samples": int(n_samples),
            **dict(meta or {}),
        }
        # Unauthenticated uploads are nonce-free and encode once; in auth
        # mode each attempt embeds that connection's server challenge, so
        # encoding happens inside the loop.
        msg = (
            wire.encode(params, meta=base_meta, compression=self.compression)
            if self.auth_key is None
            else None
        )
        last: Exception | None = None
        for attempt in range(1, max_retries + 1):
            sock = None
            try:
                sock = connect_with_retry(self.host, self.port, timeout=self.timeout)
                sock.settimeout(self.timeout)
                nonce_hex = None
                if self.auth_key is not None:
                    chal = framing.recv_frame(sock)
                    if len(chal) != len(wire.NONCE_MAGIC) + wire.NONCE_LEN or (
                        not chal.startswith(wire.NONCE_MAGIC)
                    ):
                        raise wire.WireError("bad auth challenge from server")
                    nonce_hex = chal[len(wire.NONCE_MAGIC) :].hex()
                    msg = wire.encode(
                        params,
                        meta={**base_meta, "role": "client", "nonce": nonce_hex},
                        compression=self.compression,
                        auth_key=self.auth_key,
                    )
                log.info(
                    f"[CLIENT {self.client_id}] uploading {len(msg) / 1e6:.1f} MB "
                    f"(attempt {attempt}/{max_retries})"
                )
                framing.send_frame(sock, msg)
                reply = framing.recv_frame(sock)
                agg, agg_meta = wire.decode(reply, auth_key=self.auth_key)
                if self.auth_key is not None and (
                    agg_meta.get("role") != "server"
                    or agg_meta.get("nonce") != nonce_hex
                ):
                    raise wire.WireError(
                        "aggregated reply failed the freshness check "
                        "(stale nonce or wrong role) — possible replay"
                    )
                log.info(
                    f"[CLIENT {self.client_id}] received aggregated model "
                    f"({len(reply) / 1e6:.1f} MB, clients {agg_meta.get('round_clients')})"
                )
                return agg
            except (OSError, ConnectionError, wire.WireError) as e:
                last = e
                log.info(f"[CLIENT {self.client_id}] round attempt {attempt} failed: {e}")
                if attempt < max_retries:
                    time.sleep(min(2.0**attempt, 10.0))
            finally:
                if sock is not None:
                    sock.close()
        raise ConnectionError(
            f"client {self.client_id}: round failed after {max_retries} attempts: {last}"
        )
