"""Federated client session for the cross-host demo-parity mode.

The reference's client session is: connect, gzip-pickle upload on port
12345, poll a second port every 1 s until the server opens it, download
with a retry budget (reference client1.py:276-336). Here the whole
exchange is one request/response on one connection — upload the local
params, block until the aggregated params come back on the same socket —
with connection retry/backoff standing in for the reference's
``wait_for_server`` probe loop (client1.py:298-311) but without the
probe-kills-server race (SURVEY.md §5).
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Any, Mapping

import numpy as np

from ..obs import metrics as obs_metrics
from ..utils.logging import get_logger
from . import framing, secure, wire

log = get_logger()

#: Re-home reasons (the ``fedtpu_client_rehomes_total`` label values):
#: the primary's dial budget ran out vs an established connection dying
#: before the round's reply landed.
REHOME_REASONS = ("dial-exhausted", "mid-exchange")


def _rehome_counters() -> dict:
    """Per-reason re-home counters on the default registry. The registry
    is get-or-create, so every FederatedClient in a process shares one
    family (registered only from this module — obs-metric-once)."""
    m = obs_metrics.default_registry()
    return {
        r: m.counter(
            "fedtpu_client_rehomes_total",
            help="exchanges moved to a fallback parent, by reason "
            "(dial-exhausted | mid-exchange)",
            labels={"reason": r},
        )
        for r in REHOME_REASONS
    }


def _host_params(tree: Any) -> Any:
    """Materialize every leaf of a nested param dict on host exactly once.

    The meshed TCP client (cli/comm.py ``--data-parallel``) hands exchange
    device-backed replicated arrays; ``np.asarray`` here is the one
    device->host gather, so the retry loop's per-attempt flatten/encode
    passes never re-cross the device boundary."""
    if isinstance(tree, Mapping):
        return {k: _host_params(v) for k, v in tree.items()}
    return np.asarray(tree)


def backoff_intervals(
    *,
    base: float = 1.0,
    cap: float = 15.0,
    factor: float = 2.0,
    seed: int | None = None,
):
    """Capped exponential backoff intervals with DETERMINISTIC jitter.

    The first interval is exactly ``base`` — the reference's 1 s probe
    cadence (client1.py:298-311), kept so the common case (server comes
    up within a second) connects exactly as fast as before. Every later
    interval grows by ``factor`` up to ``cap``, scaled by a jitter in
    [0.5, 1.0) drawn from ``random.Random(seed)`` — seeded, so a given
    (client, seed) retries on a reproducible schedule (tests can pin
    it), while different clients (different seeds) desynchronize instead
    of stampeding a restarting server in lockstep.
    """
    import random

    r = random.Random(seed)
    k = 0
    while True:
        if k == 0:
            yield float(base)
        else:
            yield min(float(cap), float(base) * float(factor) ** k) * (
                0.5 + 0.5 * r.random()
            )
        k += 1


def connect_with_retry(
    host: str,
    port: int,
    *,
    timeout: float = 300.0,
    poll_interval: float = 1.0,  # the reference's 1 s first-probe cadence
    max_interval: float = 15.0,
    retry_seed: int | None = None,
    abort_event: threading.Event | None = None,
) -> socket.socket:
    """Dial until the server is up or ``timeout`` elapses.

    Retries follow :func:`backoff_intervals` (first retry after exactly
    ``poll_interval``, then capped exponential growth with seeded
    jitter) instead of the reference's fixed 1 s polling — a fleet of
    clients waiting out a long server restart stops hammering it once a
    second each, without giving up any first-connect latency.

    ``abort_event`` (FederatedClient.abort / relay teardown) interrupts
    the backoff sleeps so a shutdown never waits out a dial budget."""
    deadline = time.monotonic() + timeout
    last: Exception | None = None
    sched = backoff_intervals(
        base=poll_interval, cap=max_interval, seed=retry_seed
    )
    while time.monotonic() < deadline:
        if abort_event is not None and abort_event.is_set():
            raise ConnectionError(f"dial of {host}:{port} aborted")
        try:
            sock = socket.create_connection(
                (host, port), timeout=max(0.1, deadline - time.monotonic())
            )
            return sock
        except OSError as e:
            last = e
            pause = min(next(sched), max(0.0, deadline - time.monotonic()))
            if abort_event is not None:
                if abort_event.wait(pause):
                    raise ConnectionError(
                        f"dial of {host}:{port} aborted"
                    ) from e
            else:
                time.sleep(pause)
    raise ConnectionError(f"server {host}:{port} unreachable after {timeout}s: {last}")


class FederatedClient:
    """One client's view of a federated round over TCP."""

    #: After giving up on sparse mode, re-advertise wants_delta once every
    #: this many dense uploads (recovery probe; see _gave_up_delta).
    PROBE_EVERY = 8

    def __init__(
        self,
        host: str,
        port: int,
        *,
        client_id: int,
        timeout: float = 300.0,  # the reference's TIMEOUT (client1.py:22)
        compression: str = "none",
        auth_key: bytes | None = None,
        secure_agg: bool = False,
        num_clients: int | None = None,
        fp_bits: int = secure.DEFAULT_FP_BITS,
        dp: bool = False,
        client_key: bytes | None = None,
        min_participants: int | None = None,
        secure_protocol: str = "double",
        secure_threshold: int | None = None,
        tracer=None,
        stream: bool = True,
        fallback_parents: list[tuple[str, int]] | None = None,
        rehome_dial_budget: float = 8.0,
        wire_dtype: str = "fp32",
    ):
        # Quantized streamed uploads (--wire-dtype): encode streamed
        # leaves as bf16 or per-chunk-scaled int8 (comm/quant.py) once
        # the server's reply meta advertises it accepts that encoding.
        # Streaming-path only: dense fallbacks/retries always ship fp32
        # (always correct against any peer), so the knob composes with
        # nothing that already owns the upload encoding — an explicit
        # --compression (lossy dense or topk sparse deltas) or masked
        # secure-agg uploads refuse it rather than silently stacking
        # two lossy transforms. DP composes: the server holds a lossy
        # streamed DP upload whole and re-clips it before folding
        # (comm/server.py dp containment), so quantization can never
        # widen the mechanism's sensitivity.
        wire_dtype = str(wire_dtype)
        if wire_dtype not in wire.WIRE_DTYPE_ENCS:
            raise ValueError(
                f"wire_dtype {wire_dtype!r} must be "
                f"{'|'.join(sorted(wire.WIRE_DTYPE_ENCS))}"
            )
        if wire_dtype != "fp32":
            if secure_agg:
                raise ValueError(
                    "wire_dtype quantization is incompatible with secure "
                    "aggregation: masked uploads are uniform ring "
                    "elements — quantizing them destroys mask "
                    "cancellation"
                )
            if compression != "none":
                raise ValueError(
                    f"wire_dtype={wire_dtype} needs compression='none': "
                    "the upload encoding is owned by one knob — lossy "
                    "dense compression would stack two quantizers, and "
                    "sparse topk deltas are single-frame (never "
                    "streamed)"
                )
        self.wire_dtype = wire_dtype
        #: Encodings the server's last reply advertised it accepts for
        #: streamed leaves (wire.WIRE_DTYPE_META_KEY) — the negotiation
        #: state, one reply behind like the stream-chunk advert. Empty
        #: against an old peer, so uploads stay fp32 (interop).
        self._server_wire_dtypes: tuple[str, ...] = ()
        #: What the last completed upload actually shipped (telemetry +
        #: the relay-forward span stamp).
        self.last_wire_dtype = "fp32"
        self.last_upload_bytes = 0
        if fallback_parents and (secure_agg or dp):
            # A secure-agg session is keyed to ONE server's (session,
            # round) advert and central DP to one server's resync
            # history; silently re-masking / re-basing against an
            # unrelated aggregator is never correct. Relay trees — the
            # re-homing deployment shape — refuse both modes anyway
            # (comm/server.py reply_via).
            raise ValueError(
                "fallback_parents (client re-homing) is a plain/relay-"
                "tree feature: secure aggregation and central DP bind "
                "the exchange to a single aggregator"
            )
        if rehome_dial_budget <= 0.0:
            raise ValueError(
                f"rehome_dial_budget={rehome_dial_budget} must be > 0"
            )
        if client_key is not None and auth_key is None:
            raise ValueError(
                "client_key (per-client DH identity binding) requires "
                "auth_key: the rest of the exchange is authenticated "
                "under the group key"
            )
        if dp and compression.startswith("topk"):
            raise ValueError(
                "central DP uploads are clipped dense deltas; the sparse "
                "error-feedback tier would carry unclipped mass across "
                "rounds — drop --dp or topk"
            )
        if secure_agg and num_clients is None:
            raise ValueError(
                "secure aggregation needs num_clients: each client must "
                "mask against the full advertised participant set"
            )
        # Client-side quorum floor on the secure participant set. The
        # server's keys frame defines the round's set (dropout recovery
        # shrinks it to a quorum); WITHOUT a client-side floor, a
        # compromised server (or an on-path MITM in no-auth mode) could
        # silently downgrade a client's mask-partner set to one colluding
        # member and recover its raw update. Default: the FULL fleet —
        # dropout-tolerant deployments opt in by setting this to the
        # operator's intended quorum (mirror the server's min_clients).
        if secure_protocol not in ("reveal", "double"):
            raise ValueError(
                f"secure_protocol {secure_protocol!r} must be reveal|double"
            )
        if secure_threshold is not None and secure_threshold < 2:
            raise ValueError(
                "secure_threshold < 2 would let the server reconstruct "
                "secrets from a single holder"
            )
        if (
            secure_agg
            and secure_protocol == "double"
            and num_clients is not None
            and num_clients > 254
        ):
            raise ValueError(
                "double-masking Shamir x-coordinates support <= 254 clients"
            )
        if secure_agg:
            floor = num_clients if min_participants is None else int(min_participants)
            if not 2 <= floor <= num_clients:
                raise ValueError(
                    f"min_participants={min_participants} must be in "
                    f"[2, num_clients={num_clients}]"
                )
            self.min_participants = floor
        else:
            if min_participants is not None:
                raise ValueError(
                    "min_participants is a secure-aggregation knob (the "
                    "mask-partner quorum floor); it has no meaning "
                    "without secure_agg"
                )
            self.min_participants = None
        self._topk_frac: float | None = None
        if compression.startswith("topk"):
            # Sparse ROUND-DELTA exchange: after the first (dense) round,
            # uploads carry topk(params - last_aggregate + residual) and the
            # dropped mass is accumulated client-side (error feedback), so
            # over rounds every coordinate's drift still reaches the server.
            _, self._topk_frac = wire.parse_compression(compression)
            if secure_agg:
                raise ValueError(
                    "topk compression is incompatible with secure "
                    "aggregation: masked uploads are uniform ring elements "
                    "with no sparsity to exploit"
                )
        self.host = host
        self.port = port
        # Survivable fold trees (fallback parents): the ranked parent
        # list this client walks when its current parent dies —
        # [primary, fallback 1, fallback 2, ...]. Advancing is STICKY
        # (the adoptive parent keeps this client for later rounds; a
        # restarted primary is re-ranked by restarting the client), and
        # every upload after a re-home carries wire.REHOME_META_KEY so
        # the adoptive subtree folds it as an EXTRA contributor instead
        # of counting it toward its own quorum. With fallbacks
        # configured, each dial gets ``rehome_dial_budget`` seconds of
        # the seeded backoff schedule instead of the full exchange
        # timeout — a dead parent costs one budget, not the round.
        self._parents: list[tuple[str, int]] = [(host, int(port))] + [
            (h, int(p)) for h, p in (fallback_parents or [])
        ]
        self._parent_idx = 0
        self.rehome_dial_budget = float(rehome_dial_budget)
        self._rehomed = False
        #: Re-homes performed, by reason (mirrors the
        #: fedtpu_client_rehomes_total counter labels).
        self.rehomes: dict[str, int] = {}
        self._m_rehomes = _rehome_counters()
        # abort(): prompt teardown for a client mid-exchange (the relay's
        # parent-facing leg must not wait out a socket timeout when the
        # relay closes mid-round). _live_sock tracks the attempt's
        # socket under a lock so abort() can shut it down from another
        # thread — shutdown(SHUT_RDWR) interrupts a blocked recv where a
        # bare close() would be deferred by the interpreter.
        self._abort = threading.Event()
        self._sock_lock = threading.Lock()
        self._live_sock: socket.socket | None = None
        self.client_id = client_id
        self.timeout = timeout
        self.compression = compression
        self.auth_key = auth_key
        self.secure_agg = secure_agg
        self.num_clients = num_clients
        self.fp_bits = fp_bits
        # Central DP (comm/server.py dp_clip): uploads become clipped
        # round deltas vs the caller-supplied round base; the reply is the
        # noised mean delta, applied to the base before exchange() returns
        # (callers still see an absolute aggregate). clip/noise come from
        # the server's advert.
        self.dp = dp
        # Per-client DH identity key (comm/secure.py threat model): tags
        # this client's hello and reveal frames under its OWN key so no
        # other group member can impersonate it; the relayed keys frame
        # stays group-keyed. _identity_key is the single selection both
        # tagging sites use (own key when provisioned, group otherwise).
        self.client_key = client_key
        self._identity_key = client_key if client_key is not None else auth_key
        # "double" (default): full Bonawitz double-masking — the client
        # REFUSES a server advertising the cheaper reveal protocol
        # (downgrade protection); run both ends with the same setting.
        self.secure_protocol = secure_protocol
        self.secure_threshold = secure_threshold
        # Per-(session, round) double-masking state: dealt Shamir shares
        # (retries must resend IDENTICAL shares — the server enforces
        # first-deal-wins), the self-mask seed, and the holder-side shares
        # decrypted from the shareset.
        self._round_shares: dict[tuple[bytes, int], dict] = {}
        # Highest (per session) round this instance has already masked an
        # upload for: a later exchange() refuses a replayed advert rather
        # than masking DIFFERENT weights under the same stream.
        self._used_rounds: dict[bytes, int] = {}
        # Per-(session, round) DH keypair: retries of the same round MUST
        # re-send the identical public key (the server accepts an
        # idempotent re-hello; a fresh keypair after key distribution
        # could never cancel and would doom the round).
        self._round_keys: dict[tuple[bytes, int], tuple[bytes, int, bytes]] = {}
        # Sparse-delta state (topk mode): the last aggregate this client
        # received (the delta base BOTH sides agree on, keyed by the
        # server's agg_round) and the error-feedback residual.
        self._base: dict | None = None
        self._base_round: int | None = None
        self._residual: dict | None = None
        self._warned_lossy_base = False
        # Set when this client has refused sparse mode (lossy reply
        # compression / pre-delta server): suppresses the wants_delta
        # advert so the server stops computing agg_crc for nothing — but
        # NOT permanently: every PROBE_EVERY-th dense round re-advertises,
        # so a server restarted with lossless compression is rediscovered
        # and sparse mode resumes without a client restart.
        self._gave_up_delta = False
        self._dense_rounds_since_giveup = 0
        self._probe_this_round = False
        # Observability (obs/trace.py): the server mints one trace id per
        # round and stamps it into the reply meta; this client's spans
        # (wire-upload/wire-reply plus any caller-noted client-local
        # phase) are written only once the reply reveals that identity,
        # so both sides of the wire share (trace, round). A server
        # without tracing simply omits the field — spans then carry no
        # trace id but the exchange is unchanged (old-peer interop).
        self.tracer = tracer
        self.last_trace: tuple[str | None, int | None] = (None, None)
        self._pending_spans: list[tuple[str, float, float, dict]] = []
        # Streamed-upload capability (wire.py "Streamed uploads"): the
        # server advertises its preferred chunk size in every reply's
        # meta; once seen, later uploads go leaf-by-leaf in bounded
        # chunks on a background wire thread (pack k+1 while k sends).
        # Pre-stream servers never advertise, so the first round — and
        # every exchange against an old peer — stays single-frame.
        # Masked (secure-agg) and sparse (topk) uploads stay single-frame
        # too: the former's unmask protocol needs the full contributor
        # set resolved server-side before any aggregate exists, the
        # latter's encoded size is data-dependent (no plannable header).
        self.stream = bool(stream)
        self._server_stream: int | None = None
        # Streamed-REPLY capability (wire.py "Streamed replies"): when
        # this client can decode STRH/STRC/STRT replies it says so in
        # every upload's meta; a capable server then streams the
        # aggregate back and each leaf decodes as its bytes land. Works
        # from round 1 (the advert travels client -> server). Masked
        # rounds stay dense both ways (single-aggregator protocol).
        # ``reply_leaf_sink``: optional callable ``(key, ndarray) ->
        # leaf`` applied to each PLAIN streamed-reply leaf the moment it
        # decodes — the mesh tier's hook (train/client_mesh.py) places
        # leaves onto device buffers while later chunks are still on the
        # wire, so adopt_aggregate never waits for a full host-side
        # tree. Never applied to DP/sparse replies (their deltas need
        # host arithmetic first) or dense replies.
        self.reply_leaf_sink = None
        # One-line dense-fallback reasons already logged (log each once,
        # not per round — an old peer would otherwise say it every
        # exchange).
        self._fallback_logged: set[str] = set()
        # Wire-codec step profiler (obs/profile.py, --profile-stride):
        # samples the streamed pack (leaf gather+encode) and unpack
        # (chunk decode+place) hot loops, surfacing step_wire_ms_* attrs
        # on the wire-upload/wire-reply spans. None when profiling is
        # off — the loops then run the literal pre-profiling path.
        # Re-armed lazily per exchange (the CLI installs the stride
        # after this client may have been built).
        self._wire_profiler = None
        if secure_agg and auth_key is None:
            log.warning(
                f"[CLIENT {client_id}] --secure-agg without an auth key "
                "(FEDTPU_SECRET): the DH key exchange has no integrity — "
                "an ACTIVE on-path attacker could substitute keys and "
                "unmask uploads; protection is against passive observers "
                "and the curious server only"
            )

    def exchange(
        self,
        params: Any,
        *,
        n_samples: int = 1,
        meta: Mapping[str, Any] | None = None,
        max_retries: int = 5,  # the reference's retry budget (client1.py:314)
        round_base: Any | None = None,
    ) -> dict:
        """Upload local params, return the aggregated params (nested dict).

        Retries the whole round-trip on connection errors; a server-side
        WireError (e.g. CRC mismatch after corruption) also retries with a
        fresh upload.

        With ``secure_agg`` set, the upload is the pairwise-masked
        fixed-point form (comm/secure.py): the server sees only uniform
        ring elements, never this client's raw weights. A fresh ephemeral
        DH keypair is drawn per attempt; the server relays every
        participant's public key, and each pair's mask stream is keyed by
        the DH pair secret plus the advertised (session, round) — fresh
        across rounds, and no client holds key material for pairs it does
        not belong to.

        With a ``topk`` compression, rounds after the first upload sparse
        deltas with an error-feedback residual. CONTRACT: the caller must
        adopt the returned aggregate as its model (continue local training
        FROM it, as cli/comm.py's client loop does — the standard FedAvg
        client). A caller that keeps training from its own pre-exchange
        params would carry the undelivered drift in its params AND in the
        residual, over-correcting those coordinates roughly 2x per round.
        """
        can_stream = (
            self.stream and not self.secure_agg and self._topk_frac is None
        )
        # Lazy host gather (plain streamed path only): leave device-backed
        # leaves on device so the stream packer's per-leaf np.asarray
        # overlaps the mesh-tier host gather with the first chunk sends.
        # DP needs the full host tree up front (the delta/clip math runs
        # over it), and a fallback/dense attempt gathers on demand.
        stream_flat: dict | None = None
        if can_stream and self._server_stream and not self.dp:
            stream_flat = wire.flatten_lazy(params)
        else:
            params = _host_params(params)
        if round_base is not None:
            round_base = _host_params(round_base)
        base_meta = {
            "client_id": self.client_id,
            "n_samples": int(n_samples),
            **dict(meta or {}),
        }
        if self._rehomed:
            # Sticky marker: the adoptive parent folds this client as an
            # EXTRA contributor every round (it is not in that subtree's
            # own expected count).
            base_meta[wire.REHOME_META_KEY] = 1
        if self.stream and not self.secure_agg:
            # Streamed-reply advert: plain meta, so an old server ignores
            # it and keeps sending the dense frame (interop unchanged).
            base_meta[wire.STREAM_REPLY_META_KEY] = 1
            # Quantized-reply capability (server ``--reply-dtype``): the
            # stream leaf encodings this client's decode path handles.
            # The shared stream decode already dequantizes every codec in
            # WIRE_DTYPE_ENCS, so advertise them all; the server picks at
            # most its configured one per client.
            base_meta[wire.REPLY_DTYPE_META_KEY] = sorted(
                set(wire.WIRE_DTYPE_ENCS.values())
            )
        dp_base_flat = dp_delta = None
        if self.dp:
            # ``round_base``: the params this round's local training
            # STARTED from (the previously adopted aggregate; the shared
            # init in round 1 — every client must start from the same
            # weights, enforced by the server's crc-equality check). The
            # upload is clip(params - round_base); the clip value arrives
            # in the server's advert, so the final clipping happens inside
            # the attempt loop.
            if round_base is None:
                raise ValueError(
                    "central DP needs round_base: the params this round's "
                    "training started from"
                )
            dp_base_flat = {
                k: np.asarray(v, np.float32)
                for k, v in wire.flatten_params(round_base).items()
            }
            flatp = wire.flatten_params(params)
            if not wire.shapes_compatible(flatp, dp_base_flat):
                raise ValueError(
                    "round_base tensor set/shapes do not match params"
                )
            dp_delta = {
                k: np.asarray(flatp[k], np.float32) - dp_base_flat[k]
                for k in flatp
            }
            base_meta["dp"] = True
            base_meta["dp_base_crc"] = wire.flat_crc32(dp_base_flat)
        flat = (
            wire.flatten_params(params)
            if self.secure_agg and not self.dp
            else None
        )
        # The plain (no auth, no masking, no sparse-delta) upload encodes
        # once; auth embeds the per-connection challenge, secure mode embeds
        # the per-round masks, and topk mode picks sparse-vs-dense per
        # attempt, so those encode inside the attempt loop.
        msg = (
            wire.encode(params, meta=base_meta, compression=self.compression)
            if self.auth_key is None
            and not self.secure_agg
            and self._topk_frac is None
            and not self.dp
            and not (can_stream and self._server_stream)
            else None
        )
        last: Exception | None = None
        this_call: tuple[bytes, int] | None = None  # (session, round) masked now
        fresh_parent = False  # just re-homed: next dial is this parent's first
        for attempt in range(1, max_retries + 1):
            sock = None
            sparse_in_flight = False  # this attempt's delta hit the wire
            upload_timing = None
            upload_started = None  # (t_unix, t0, bytes): send began
            try:
                if self._abort.is_set():
                    raise ConnectionError(
                        f"client {self.client_id}: exchange aborted"
                    )
                # retry_seed=client_id: each client's dial-retry jitter is
                # deterministic but fleet-desynchronized. With fallback
                # parents, each dial gets the bounded re-home budget so a
                # dead parent costs seconds, not the exchange timeout.
                sock = connect_with_retry(
                    self.host, self.port,
                    timeout=(
                        min(self.timeout, self.rehome_dial_budget)
                        if len(self._parents) > 1
                        else self.timeout
                    ),
                    retry_seed=self.client_id,
                    abort_event=self._abort,
                )
                sock.settimeout(self.timeout)
                with self._sock_lock:
                    self._live_sock = sock
                if self._abort.is_set():
                    # abort() may have landed between the dial returning
                    # and _live_sock registration — its socket shutdown
                    # then missed this connection, so re-check here or
                    # the exchange would proceed into a blocking recv.
                    raise ConnectionError(
                        f"client {self.client_id}: exchange aborted"
                    )
                # A re-homed attempt is this parent's FIRST contact: skip
                # the failed-attempt mode-diagnosis peek below (it would
                # stall the re-upload by the peek window against a
                # healthy adoptive parent, for a failure that happened
                # elsewhere).
                first_contact = fresh_parent
                fresh_parent = False
                nonce_hex = None
                attempt_meta = dict(base_meta)
                upload = params
                if self.auth_key is not None:
                    chal = framing.recv_frame(sock)
                    if len(chal) != len(wire.NONCE_MAGIC) + wire.NONCE_LEN or (
                        not chal.startswith(wire.NONCE_MAGIC)
                    ):
                        raise wire.WireError("bad auth challenge from server")
                    nonce_hex = chal[len(wire.NONCE_MAGIC) :].hex()
                    attempt_meta.update(role="client", nonce=nonce_hex)
                if (
                    not self.secure_agg
                    and not self.dp
                    and attempt > 1
                    and not first_contact
                ):
                    # Mode diagnosis after a failed first attempt: a
                    # secure/DP/auth server speaks FIRST (round advert /
                    # DP advert / nonce challenge), which a plain client
                    # never reads — its upload then dies as a malformed
                    # hello and naive retries burn the whole budget the
                    # same way. One short peek turns that loop into a
                    # clean, non-retryable refusal naming the fix.
                    # Window scaled off the configured timeout: a 0.3 s
                    # constant would miss the advert on a slow link and
                    # silently fall back to burning the retry budget.
                    sock.settimeout(min(self.timeout, 2.0))
                    try:
                        stray = framing.recv_frame(sock)
                    except (OSError, ConnectionError):
                        stray = None
                    finally:
                        sock.settimeout(self.timeout)
                    if stray is not None:
                        if bytes(stray[:4]) == wire.ROUND_MAGIC:
                            raise secure.SecureAggError(
                                "server is running --secure-agg; run this "
                                "client with --secure-agg"
                                + (
                                    " (and drop topk: sparse deltas "
                                    "cannot be masked — masked uploads "
                                    "are uniform ring elements with no "
                                    "sparsity)"
                                    if self._topk_frac is not None
                                    else ""
                                )
                            )
                        if bytes(stray[:4]) == wire.DP_MAGIC:
                            raise wire.ModeError(
                                "server is running --dp-clip; run this "
                                "client with --dp"
                            )
                        if bytes(stray[:4]) == wire.NONCE_MAGIC:
                            raise wire.ModeError(
                                "server requires authentication; set "
                                "FEDTPU_SECRET for this client"
                            )
                        raise wire.ModeError(
                            "server opened with an unexpected frame "
                            f"({bytes(stray[:4])!r}) — client/server "
                            "mode mismatch"
                        )
                sitting_out = False
                share_st = None
                if self.dp:
                    # DP handshake: identify ourselves (the server's
                    # Poisson cohort sampler needs the id before any model
                    # bytes move), then read the advert — clip bound,
                    # noise multiplier, sampling rate, and whether THIS
                    # client is in the round's cohort. Fail fast if the
                    # server isn't in DP mode (its next frame would be
                    # something else).
                    import struct as _struct

                    sock.settimeout(min(self.timeout, 30.0))
                    try:
                        adv = framing.recv_frame(sock)
                    except socket.timeout:
                        # ModeError, not WireError: retries would stall
                        # identically against a non-DP server.
                        raise wire.ModeError(
                            "server sent no DP advert — is it running "
                            "with --dp-clip?"
                        ) from None
                    finally:
                        sock.settimeout(self.timeout)
                    n_magic = len(wire.DP_MAGIC)
                    if len(adv) != n_magic + 24 or not adv.startswith(
                        wire.DP_MAGIC
                    ):
                        raise wire.ModeError("bad DP advert from server")
                    dp_clip, dp_noise, dp_q = _struct.unpack(
                        "<ddd", adv[n_magic:]
                    )
                    if not dp_clip > 0.0:
                        raise wire.WireError(
                            f"DP advert carries clip={dp_clip}"
                        )
                    if not 0.0 < dp_q <= 1.0:
                        raise wire.WireError(
                            f"DP advert carries sampling rate q={dp_q}"
                        )
                    # Identify ourselves; the server answers the round's
                    # cohort verdict for this id.
                    framing.send_frame(
                        sock,
                        wire.DPID_MAGIC + _struct.pack("<q", self.client_id),
                    )
                    verdict = framing.recv_frame(sock)
                    if len(verdict) != len(wire.DPCOHORT_MAGIC) + 1 or (
                        not verdict.startswith(wire.DPCOHORT_MAGIC)
                    ):
                        raise wire.WireError("bad DP cohort verdict")
                    if verdict[-1] == 0:
                        if dp_q >= 1.0:
                            raise wire.WireError(
                                "server claims this client is not sampled "
                                "under full participation (q=1)"
                            )
                        # Sitting the round out: no upload — but wait for
                        # the round's reply so our base tracks the fleet's.
                        if self.auth_key is not None:
                            # Prove key knowledge before the server
                            # registers us for the reply (anti-hijack).
                            import hmac as _hmac

                            framing.send_frame(
                                sock,
                                wire.DPSKIP_MAGIC
                                + _hmac.new(
                                    self.auth_key,
                                    wire.DPSKIP_DOMAIN
                                    + bytes.fromhex(nonce_hex)
                                    + _struct.pack("<q", self.client_id),
                                    "sha256",
                                ).digest(),
                            )
                        log.info(
                            f"[CLIENT {self.client_id}] sitting out this "
                            f"round (Poisson cohort sampling q={dp_q}); "
                            "waiting for the round reply"
                        )
                        sitting_out = True
                    else:
                        # Client-side clipping (the server re-clips in
                        # plain mode; under secure-agg it cannot, so this
                        # is the honest-client clip the guarantee assumes).
                        clipped, norm, scale = wire.clip_flat(
                            dp_delta, dp_clip
                        )
                        log.info(
                            f"[CLIENT {self.client_id}] DP round: update "
                            f"norm {norm:.4g}, clip {dp_clip} (scale "
                            f"{scale:.3g}), noise x{dp_noise}"
                        )
                        if self.secure_agg:
                            flat = clipped  # quantize+mask the clipped delta
                        else:
                            upload = clipped
                if self.secure_agg and not sitting_out:
                    import struct as _struct

                    # A secure server adverts immediately after accept; if
                    # nothing arrives quickly the server is almost surely
                    # running without --secure-agg. Fail fast and
                    # non-retryably (retries would stall identically)
                    # instead of blocking the full socket timeout.
                    sock.settimeout(min(self.timeout, 30.0))
                    try:
                        adv = framing.recv_frame(sock)
                    except socket.timeout:
                        raise secure.SecureAggError(
                            "server sent no round advert — is it running "
                            "with --secure-agg?"
                        ) from None
                    finally:
                        sock.settimeout(self.timeout)
                    n_magic = len(wire.ROUND_MAGIC)
                    if len(adv) != n_magic + 8 + wire.SESSION_LEN + 1 or (
                        not adv.startswith(wire.ROUND_MAGIC)
                    ):
                        raise wire.WireError("bad round advert from server")
                    round_no = _struct.unpack("<Q", adv[n_magic : n_magic + 8])[0]
                    if round_no >= 2**63:
                        raise wire.WireError(
                            f"round advert {round_no} out of range"
                        )
                    session = bytes(
                        adv[n_magic + 8 : n_magic + 8 + wire.SESSION_LEN]
                    )
                    # Protocol pin, not negotiation: a mismatch is refused
                    # non-retryably — otherwise a malicious advert could
                    # downgrade double-masking to the weaker reveal round.
                    want_proto = (
                        secure.PROTO_DOUBLE
                        if self.secure_protocol == "double"
                        else secure.PROTO_REVEAL
                    )
                    if adv[-1] != want_proto:
                        raise secure.SecureAggError(
                            f"server advertises secure protocol "
                            f"{'double' if adv[-1] else 'reveal'}, this "
                            f"client is configured for "
                            f"{self.secure_protocol} — refusing (set "
                            "--secure-protocol identically on both ends)"
                        )
                    # Freshness: retries of THIS exchange may legitimately
                    # re-mask the same weights for the same (session,
                    # round); a replay of an earlier exchange's round would
                    # mask different weights under the same stream, which
                    # is exactly the differencing attack — refuse.
                    prev = self._used_rounds.get(session, -1)
                    if round_no <= prev and (session, round_no) != this_call:
                        raise secure.SecureAggError(
                            f"server replayed round {round_no} (already "
                            f"masked up to round {prev} this session) — "
                            "refusing to reuse a mask stream"
                        )
                    this_call = (session, round_no)
                    # DH key exchange (relayed by the server): send our
                    # ephemeral public key, receive every participant's,
                    # derive per-pair mask secrets. One keypair per
                    # (session, round), REUSED across retries — the server
                    # treats a same-key re-hello as idempotent, so a retry
                    # after a transient wire error still completes the
                    # round instead of being dropped as a key swap.
                    if (session, round_no) not in self._round_keys:
                        # Seed-derived keypair: double-masking Shamir-shares
                        # the seed so the fleet can reconstruct this
                        # client's pair masks if it dies mid-round.
                        sk_seed = os.urandom(secure.SEED_LEN)
                        kpriv, kpub = secure.dh_keypair(entropy=sk_seed)
                        self._round_keys[(session, round_no)] = (
                            sk_seed, kpriv, kpub,
                        )
                    sk_seed, priv, pub = self._round_keys[(session, round_no)]
                    hello = (
                        wire.PUBKEY_MAGIC
                        + _struct.pack("<q", self.client_id)
                        + pub
                    )
                    if self.auth_key is not None:
                        hello += secure.pubkey_tag(
                            self._identity_key,
                            session, round_no, self.client_id, pub,
                        )
                    framing.send_frame(sock, hello)
                    keys_frame = framing.recv_frame(sock)
                    # The keys frame defines the round's participant set —
                    # the full fleet, or the quorum subset that survived
                    # the server's key grace window (dropout recovery).
                    participants, pair_secrets = self._parse_keys_frame(
                        keys_frame, priv, session, round_no
                    )
                    share_st = None
                    if self.secure_protocol == "double":
                        # Share distribution (Bonawitz §6): deal Shamir
                        # shares of (b seed, key seed) through the server;
                        # the share-complete set U2 becomes the mask set.
                        share_st = self._double_share_exchange(
                            sock, participants, pair_secrets, sk_seed,
                            session, round_no,
                        )
                        mask_set = share_st["u2"]
                    else:
                        mask_set = participants
                    upload = secure.masked_upload(
                        flat,
                        pair_secrets=pair_secrets,
                        round_index=round_no,
                        client_id=self.client_id,
                        participants=mask_set,
                        fp_bits=self.fp_bits,
                        session=session,
                    )
                    if share_st is not None:
                        # The self-mask: stays on this upload until the
                        # unmask round reconstructs b from OTHER holders'
                        # shares — what makes a false death claim useless.
                        secure.apply_self_stream(
                            upload, share_st["b_seed"], session, round_no,
                            self.client_id, add=True,
                        )
                    self._used_rounds[session] = max(prev, round_no)
                    attempt_meta.update(
                        secure=True,
                        fp_bits=self.fp_bits,
                        round=round_no,
                        participants=len(mask_set),
                    )
                attempt_compression = self.compression
                delta_flat = sent_flat = None
                if not sitting_out:
                    if self._topk_frac is not None:
                        upload, attempt_compression, delta_flat, sent_flat = (
                            self._prepare_topk_upload(
                                params, attempt, attempt_meta
                            )
                        )
                    # Streamed upload: first attempt only — a retry may be
                    # recovering from a server that stopped speaking the
                    # stream protocol (restart, downgrade), and the dense
                    # single frame is always correct.
                    use_stream = (
                        can_stream
                        and self._server_stream is not None
                        and attempt == 1
                    )
                    if not use_stream:
                        self._log_dense_fallback(attempt)
                    if use_stream:
                        up_flat = (
                            stream_flat
                            if stream_flat is not None
                            else wire.flatten_lazy(upload)
                        )
                        # Negotiated quantization (--wire-dtype): upgrade
                        # the stream's leaf encoding only when the
                        # server's last reply advertised it decodes this
                        # encoding; old peers never advertise, so they
                        # keep receiving fp32. The meta stamp lets the
                        # server label the round's uploads by wire dtype.
                        stream_compression = attempt_compression
                        used_dtype = "fp32"
                        enc = wire.WIRE_DTYPE_ENCS[self.wire_dtype]
                        if (
                            self.wire_dtype != "fp32"
                            and enc in self._server_wire_dtypes
                        ):
                            stream_compression = enc
                            used_dtype = self.wire_dtype
                            attempt_meta["wire_dtype"] = self.wire_dtype
                        t_up_unix = time.time()
                        t_up0 = time.monotonic()
                        upload_started = (t_up_unix, t_up0, 0)
                        sent, chunks, overlap_s, wire_attrs = (
                            self._stream_upload(
                                sock, up_flat, attempt_meta,
                                stream_compression, nonce_hex,
                            )
                        )
                        self.last_wire_dtype = used_dtype
                        self.last_upload_bytes = sent
                        upload_timing = (
                            t_up_unix, time.monotonic() - t_up0, sent,
                            {"chunks": chunks,
                             "overlap_s": round(overlap_s, 6),
                             "wire_dtype": used_dtype,
                             **wire_attrs},
                        )
                    else:
                        if stream_flat is not None:
                            # Dense fallback from the lazy path: gather
                            # whatever the packer hasn't already cached,
                            # writing the host arrays back so further
                            # retries reuse them instead of re-gathering
                            # the whole model off-device each attempt.
                            for k, v in stream_flat.items():
                                stream_flat[k] = np.asarray(v)
                            upload = dict(stream_flat)
                        if (
                            self.auth_key is not None
                            or self.secure_agg
                            or self._topk_frac is not None
                            or self.dp
                            or msg is None
                        ):
                            # Fresh encode per attempt: the nonce and/or
                            # round (and with them the masks), or the
                            # sparse-vs-dense choice, change between
                            # connections.
                            msg = wire.encode(
                                upload,
                                meta=attempt_meta,
                                compression=attempt_compression,
                                auth_key=self.auth_key,
                            )
                        log.info(
                            f"[CLIENT {self.client_id}] uploading "
                            f"{len(msg) / 1e6:.1f} MB "
                            f"(attempt {attempt}/{max_retries})"
                        )
                        sparse_in_flight = delta_flat is not None
                        t_up_unix = time.time()
                        t_up0 = time.monotonic()
                        upload_started = (t_up_unix, t_up0, len(msg))
                        framing.send_frame(sock, msg)
                        self.last_wire_dtype = "fp32"
                        self.last_upload_bytes = len(msg)
                        upload_timing = (
                            t_up_unix, time.monotonic() - t_up0, len(msg),
                            None,
                        )
                else:
                    upload_timing = None
                # The reply window spans from here to the final reply
                # frame (through any unmask/reveal sub-rounds): from the
                # client's clock it covers straggler wait + server agg +
                # the reply transfer — the obs timeline subtracts the
                # server's measured agg/reply spans to isolate the wait.
                t_rep_unix = time.time()
                t_rep0 = time.monotonic()
                reply = framing.recv_frame(sock)
                if (
                    self.secure_agg
                    and self.secure_protocol == "double"
                    and share_st is not None
                    and bytes(reply[:4]) == secure.UNMASK_MAGIC
                ):
                    # Unmask round (every double-mask round): respond with
                    # b-shares for ALIVE dealers and key-seed shares for
                    # DEAD ones — never both for the same id; the parse
                    # refuses overlapping claims, and the checks below pin
                    # the claimed partition to this round's U2.
                    reply = self._answer_unmask(
                        sock, bytes(reply), share_st, session, round_no
                    )
                elif (
                    self.secure_agg
                    and self.secure_protocol == "reveal"
                    and bytes(reply[:4]) == secure.REVEAL_MAGIC
                ):
                    # Dropout reveal round: some keyed participant never
                    # uploaded; disclose our pair secrets with the dead so
                    # the server can cancel their mask halves (privacy
                    # analysis in comm/secure.py — a revealed secret only
                    # unlocks THIS round's streams for pairs whose other
                    # end contributed nothing).
                    # Reveal frames ride this client's OWN identity key
                    # when provisioned (comm/secure.py threat model): a
                    # group-keyed forgery naming a victim that actually
                    # uploaded then fails closed here.
                    dead = secure.parse_reveal_request(
                        bytes(reply),
                        session=session,
                        round_index=round_no,
                        auth_key=self._identity_key,
                    )
                    bad = [
                        d for d in dead
                        if d == self.client_id or d not in pair_secrets
                    ]
                    if bad:
                        raise secure.SecureAggError(
                            f"reveal request names invalid partners {bad}"
                        )
                    framing.send_frame(
                        sock,
                        secure.build_reveal_response(
                            {d: pair_secrets[d] for d in dead},
                            session=session,
                            round_index=round_no,
                            client_id=self.client_id,
                            auth_key=self._identity_key,
                        ),
                    )
                    reply = framing.recv_frame(sock)
                if bytes(reply[:4]) == wire.STREAM_MAGIC:
                    # Chunk-streamed reply (wire.py "Streamed replies"):
                    # the header frame already arrived; leaves decode —
                    # and, on a meshed client, land on device — as the
                    # remaining chunks come off the wire.
                    agg_flat, agg_meta, reply_bytes, reply_wire_attrs = (
                        self._recv_stream_reply(sock, reply, nonce_hex)
                    )
                    agg = wire.unflatten_params(agg_flat)
                else:
                    agg, agg_meta = wire.decode(
                        reply, auth_key=self.auth_key
                    )
                    reply_bytes = len(reply)
                    reply_wire_attrs = {}
                reply_timing = (
                    t_rep_unix, time.monotonic() - t_rep0, reply_bytes,
                    reply_wire_attrs or None,
                )
                if self.auth_key is not None and (
                    agg_meta.get("role") != "server"
                    or agg_meta.get("nonce") != nonce_hex
                ):
                    raise wire.WireError(
                        "aggregated reply failed the freshness check "
                        "(stale nonce or wrong role) — possible replay"
                    )
                # Capability negotiation (one reply behind, like the
                # sparse tier's agg_crc): adopt/refresh the server's
                # streamed-upload advert. A reply without the field —
                # old server, or one restarted with streaming off —
                # drops us back to single-frame uploads.
                try:
                    adv_stream = int(agg_meta.get(wire.STREAM_META_KEY, 0))
                except (TypeError, ValueError):
                    adv_stream = 0
                self._server_stream = (
                    adv_stream
                    if 0
                    < adv_stream
                    <= framing.MAX_FRAME - wire.STREAM_CHUNK_OVERHEAD
                    else None
                )
                # Wire-dtype advert (same one-reply-behind pattern): the
                # list of stream leaf encodings the server accepts. Only
                # encodings we recognize survive — a future server
                # advertising encodings this client never heard of must
                # not trick it into sending one.
                adv_encs = agg_meta.get(wire.WIRE_DTYPE_META_KEY)
                self._server_wire_dtypes = tuple(
                    str(e)
                    for e in (
                        adv_encs if isinstance(adv_encs, (list, tuple)) else ()
                    )
                    if str(e) in wire.WIRE_DTYPE_ENCS.values()
                )
                self._flush_spans(agg_meta, upload_timing, reply_timing)
                if self.secure_agg and this_call is not None:
                    # Round complete: drop this round's (and any older)
                    # per-round keypair/share state — _used_rounds already
                    # forbids re-entering them, and seeds for finished
                    # rounds must not linger in memory round after round.
                    # Guarded on this_call: a sitting-out sampled round
                    # never ran the secure handshake, so (session,
                    # round_no) are unbound there.
                    done_session, done_round = this_call
                    for store in (self._round_keys, self._round_shares):
                        for k in [
                            k
                            for k in store
                            if k[0] == done_session and k[1] <= done_round
                        ]:
                            del store[k]
                log.info(
                    f"[CLIENT {self.client_id}] received aggregated model "
                    f"({reply_bytes / 1e6:.1f} MB, clients {agg_meta.get('round_clients')})"
                )
                if self.dp:
                    if agg_meta.get("dp_reply") == "noop":
                        # Empty Poisson cohort: a no-op round — nothing
                        # was aggregated or released; keep the base.
                        log.info(
                            f"[CLIENT {self.client_id}] no-op round "
                            "(empty sampled cohort); keeping the round base"
                        )
                        return wire.unflatten_params(dp_base_flat)
                    # The DP reply is the noised mean DELTA (the server
                    # never held absolute weights); apply it to the round
                    # base so callers still receive an absolute aggregate.
                    if agg_meta.get("dp_reply") == "resync":
                        # The server noticed our base was stale (a missed
                        # reply): the payload is the SEQUENCE of retained
                        # post-noise round deltas under keys "0", "1", ...
                        # Replay each round's fp32 addition in order — the
                        # same arithmetic every current client performed —
                        # so the resynced base matches the fleet's
                        # BIT-EXACTLY (a pre-summed delta would land ulps
                        # away, fp32 addition being non-associative, and
                        # fail the next round's crc agreement).
                        try:
                            n_rounds = int(agg_meta["dp_resync_rounds"])
                        except (KeyError, TypeError, ValueError):
                            raise wire.WireError(
                                "resync reply missing dp_resync_rounds"
                            ) from None
                        cur = dp_base_flat
                        for i in range(n_rounds):
                            if str(i) not in agg:
                                raise wire.WireError(
                                    f"resync reply missing round delta {i}"
                                )
                            step = wire.flatten_params(agg[str(i)])
                            if not wire.shapes_compatible(step, cur):
                                raise wire.WireError(
                                    f"resync delta {i} shapes do not "
                                    "match the base"
                                )
                            cur = {
                                k: cur[k] + np.asarray(step[k], np.float32)
                                for k in cur
                            }
                        log.info(
                            f"[CLIENT {self.client_id}] stale round base "
                            f"resynced: replayed {n_rounds} retained "
                            "round delta(s)"
                        )
                        return wire.unflatten_params(cur)
                    if agg_meta.get("dp_reply") != "delta":
                        raise wire.WireError(
                            "DP reply missing dp_reply=delta marker"
                        )
                    reply_base_crc = agg_meta.get("dp_base_crc")
                    if reply_base_crc is not None and int(
                        reply_base_crc
                    ) != int(base_meta["dp_base_crc"]):
                        # The round's delta applies to a base we do not
                        # hold (we are stale — e.g. a missed reply
                        # followed by sitting a sampled round out).
                        # Applying it would compound onto the wrong base
                        # and void the server's resync window; keep our
                        # base and resync on the next contributing round.
                        log.info(
                            f"[CLIENT {self.client_id}] round delta "
                            "targets a different base than ours (stale "
                            "base); keeping the base — the next "
                            "contributing round resyncs it"
                        )
                        return wire.unflatten_params(dp_base_flat)
                    agg_flat = wire.flatten_params(agg)
                    if not wire.shapes_compatible(agg_flat, dp_base_flat):
                        raise wire.WireError(
                            "DP reply delta shapes do not match the base"
                        )
                    absolute = {
                        k: dp_base_flat[k]
                        + np.asarray(agg_flat[k], np.float32)
                        for k in agg_flat
                    }
                    return wire.unflatten_params(absolute)
                if self._topk_frac is not None:
                    self._finish_topk(agg, agg_meta, delta_flat, sent_flat)
                return agg
            except (OSError, ConnectionError, wire.WireError) as e:
                last = e
                if sparse_in_flight:
                    # The sparse upload reached (or may have reached) the
                    # server before the failure — e.g. the round was
                    # aggregated but the reply frame was lost. Its delta
                    # embedded the residual, so retaining the residual
                    # across the dense retry could deliver that mass twice
                    # (the dense retry ships the full params, and the next
                    # sparse delta would re-add the residual). The
                    # ambiguity resolves conservatively: drop it.
                    self._residual = None
                log.info(f"[CLIENT {self.client_id}] round attempt {attempt} failed: {e}")
                if self._abort.is_set():
                    # abort() means TEARDOWN: burning the remaining
                    # retries (each with its backoff sleep) would hold
                    # the caller — a closing relay's forward thread —
                    # for the whole budget.
                    break
                failed_upload = upload_timing
                if failed_upload is None and upload_started is not None:
                    # Died mid-send: still a wire-upload window worth a
                    # span (the re-home's first-attempt evidence).
                    failed_upload = (
                        upload_started[0],
                        time.monotonic() - upload_started[1],
                        upload_started[2],
                        None,
                    )
                if (
                    attempt < max_retries
                    and not self._abort.is_set()
                    and self._rehome(
                        "dial-exhausted" if sock is None else "mid-exchange",
                        err=e,
                        failed_upload=failed_upload,
                    )
                ):
                    # Re-homed: the next attempt dials the adoptive
                    # parent NOW — the inter-attempt backoff exists for a
                    # server that may come back, and this one will not.
                    base_meta[wire.REHOME_META_KEY] = 1
                    msg = None  # re-encode: the meta gains the marker
                    fresh_parent = True
                    continue
                if attempt < max_retries:
                    time.sleep(min(2.0**attempt, 10.0))
            finally:
                with self._sock_lock:
                    self._live_sock = None
                if sock is not None:
                    sock.close()
        raise ConnectionError(
            f"client {self.client_id}: round failed after {max_retries} attempts: {last}"
        )

    # ------------------------------------------------- re-homing / abort
    def _rehome(
        self,
        reason: str,
        *,
        err: Exception | None = None,
        failed_upload=None,
    ) -> bool:
        """Advance to the next parent in the ranked fallback list.

        Returns False when there is no parent left to try (the caller
        then follows the classic retry path against the last parent).
        The move is sticky — later rounds keep exchanging with the
        adoptive parent and keep stamping the re-home marker, so it
        keeps folding this client as an extra contributor. The failed
        attempt's upload window (when any bytes hit the wire) is
        buffered as a ``wire-upload`` span with ``rehome_failed=1`` —
        the obs timeline shows the re-home as a second upload span on
        the adoptive round's trace."""
        if self._parent_idx + 1 >= len(self._parents):
            return False
        self._parent_idx += 1
        self.host, self.port = self._parents[self._parent_idx]
        self._rehomed = True
        # Capabilities and bases learned from the dead parent do not
        # transfer: re-advertise from scratch (dense upload — the
        # adoptive server's stream advert arrives with its first reply)
        # and abandon the sparse-delta base (the adoptive parent's
        # aggregate history is unrelated; a delta against the old base
        # would be refused and burn a retry).
        self._server_stream = None
        self._server_wire_dtypes = ()
        self._base = self._base_round = None
        self.rehomes[reason] = self.rehomes.get(reason, 0) + 1
        self._m_rehomes[reason].inc()
        if failed_upload is not None:
            # (t_unix, dur_s, bytes, extra) — the attempt whose upload
            # hit the dead parent's wire before the failure.
            self.note_phase(
                "wire-upload",
                failed_upload[0],
                failed_upload[1],
                client=self.client_id,
                bytes=failed_upload[2],
                rehome_failed=1,
            )
        log.warning(
            f"[CLIENT {self.client_id}] re-homing ({reason}"
            + (f": {err}" if err is not None else "")
            + f") -> fallback parent {self.host}:{self.port} "
            f"({self._parent_idx}/{len(self._parents) - 1})"
        )
        return True

    def abort(self) -> None:
        """Prompt teardown for an in-flight exchange (relay close(),
        operator shutdown): interrupt the dial backoff and shut the live
        socket down so a blocked recv fails NOW instead of waiting out
        its timeout. A later exchange() raises immediately."""
        self._abort.set()
        with self._sock_lock:
            s = self._live_sock
        if s is not None:
            # shutdown, then close: close() alone is deferred by the
            # interpreter while another thread is blocked in a syscall
            # on the fd (the faults-layer prompt-close lesson, PR 6).
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    # ------------------------------------------------- streamed uploads
    def _armed_wire_profiler(self):
        """The wire-codec StepProfiler with a fresh window, or None when
        profiling is off (maybe_step_profiler re-checked per call — the
        CLI installs the stride after construction, the trainers'
        _armed_profiler pattern)."""
        from ..obs.profile import maybe_step_profiler

        prof = self._wire_profiler
        if prof is None:
            prof = self._wire_profiler = maybe_step_profiler("wire")
        if prof is not None:
            prof.begin_window()
        return prof

    def _stream_upload(
        self,
        sock: socket.socket,
        flat: dict,
        meta: dict,
        compression: str,
        nonce_hex: str | None,
    ) -> tuple[int, int, float, dict]:
        """Ship one upload as header + chunk frames + trailer (wire.py
        "Streamed uploads"): leaves are gathered/encoded one at a time on
        THIS thread while a background wire thread (framing.
        PipelinedSender) sends the chunks already packed — the compute/
        comms overlap the single-frame path cannot have. Returns
        ``(bytes sent, chunk count, overlap seconds, wire attrs)``
        where overlap is the pack+send time hidden by running the two
        concurrently and wire attrs are the sampled per-leaf pack
        timings (``step_wire_ms_*``, {} when profiling is off) for the
        wire-upload span.

        Leaves materialized here are cached back into ``flat`` so a
        dense fallback attempt never re-crosses the device boundary."""
        tensors, payload_nbytes = wire.plan_stream(flat, compression)
        chunk_bytes = int(self._server_stream or wire.DEFAULT_STREAM_CHUNK)
        nonce = bytes.fromhex(nonce_hex) if nonce_hex else b""
        header = wire.encode_stream_header(
            tensors,
            meta=meta,
            chunk_bytes=chunk_bytes,
            payload_nbytes=payload_nbytes,
            auth_key=self.auth_key,
            direction="up",
        )
        log.info(
            f"[CLIENT {self.client_id}] streaming "
            f"{payload_nbytes / 1e6:.1f} MB upload in "
            f"{-(-payload_nbytes // chunk_bytes)} chunk(s) of "
            f"<= {chunk_bytes / 1e6:.1f} MB"
        )
        t0 = time.monotonic()
        # ACKed header: a peer that stopped speaking the stream protocol
        # fails here, before any model bytes move.
        framing.send_frame(sock, header)
        sender = framing.PipelinedSender(sock)
        pack_s = 0.0
        seq = 0
        sent = len(header)
        buf = bytearray()
        try:
            def _flush(final: bool = False) -> None:
                nonlocal buf, seq, sent
                while len(buf) >= chunk_bytes or (final and buf):
                    chunk = bytes(buf[:chunk_bytes])
                    del buf[:chunk_bytes]
                    frame = wire.encode_stream_chunk(
                        seq,
                        chunk,
                        auth_key=self.auth_key,
                        nonce=nonce,
                        direction="up",
                    )
                    sender.send(frame)
                    sent += len(frame)
                    seq += 1

            wire_prof = self._armed_wire_profiler()
            for t in tensors:
                key = t["key"]
                sampled = wire_prof.tick() if wire_prof is not None else False
                tp0 = time.monotonic()
                leaf = flat[key]
                if not isinstance(leaf, wire.PreEncoded) and not isinstance(
                    leaf, np.ndarray
                ):
                    # The one host gather for a device-backed leaf;
                    # cached so retries reuse it.
                    leaf = flat[key] = np.asarray(leaf)
                data = wire.encode_stream_leaf(leaf, t["enc"])
                leaf_dt = time.monotonic() - tp0
                pack_s += leaf_dt
                if sampled:
                    # One pack "step" = one leaf's host gather + encode
                    # — the loop the step profiler never covered
                    # (PR-12 residual).
                    wire_prof.note("wire", leaf_dt)
                buf += data
                _flush()
            _flush(final=True)
            # ACKed trailer: the upload-complete handshake (the dense
            # path's per-frame ACK, paid once per upload instead).
            sender.send(
                wire.encode_stream_end(
                    seq, auth_key=self.auth_key, nonce=nonce, direction="up"
                ),
                await_ack=True,
            )
            send_s = sender.close()
        except BaseException:
            try:
                sender.close()
            except (OSError, wire.WireError, ConnectionError):
                pass
            raise
        wall = max(time.monotonic() - t0, 1e-9)
        overlap_s = max(0.0, pack_s + send_s - wall)
        return (
            sent, seq, overlap_s,
            wire_prof.span_attrs() if wire_prof is not None else {},
        )

    def _log_dense_fallback(self, attempt: int) -> None:
        """One line naming WHY this upload goes dense while the streamed
        shape exists — the silent fallbacks (topk, secure-agg, old peer,
        retry) were otherwise indistinguishable from streaming working.
        Each distinct reason logs once per client lifetime; the server
        counts them on /metrics (``stream_fallbacks_total``)."""
        if not self.stream:
            reason = "--no-stream-upload"
        elif self.secure_agg:
            reason = "secure-agg (masked uploads are single-frame by design)"
        elif self._topk_frac is not None:
            reason = "topk (payload size is data-dependent; nothing to plan)"
        elif self._server_stream is None:
            reason = "no stream advert seen yet (old peer, or round 1)"
        else:
            reason = (
                f"retry attempt {attempt} (dense is always correct after "
                "a failed streamed attempt)"
            )
        if reason not in self._fallback_logged:
            self._fallback_logged.add(reason)
            log.info(
                f"[CLIENT {self.client_id}] upload falls back to a dense "
                f"single frame: {reason}"
            )

    def _recv_stream_reply(
        self, sock: socket.socket, header, nonce_hex: str | None
    ) -> tuple[dict, dict, int, dict]:
        """Receive one chunk-streamed aggregate reply (wire.py "Streamed
        replies"): decode each leaf the moment its bytes complete. In
        auth mode every frame's tag verifies under the REPLY-direction
        HMAC domain before any byte is trusted, so a reflected upload
        chunk (valid under the upload domain, same nonce and seq) can
        never pass as aggregate data. Plain (non-DP, non-sparse) replies
        pass each decoded leaf through ``reply_leaf_sink`` when set —
        the mesh tier's on-device placement — while later chunks are
        still in flight. Returns ``(flat leaves, meta, bytes read, wire
        attrs)`` — the last being the sampled per-leaf unpack timings
        (``step_wire_ms_*``, {} when profiling is off) for the
        wire-reply span."""
        tensors, meta, _chunk_bytes, payload_nbytes = (
            wire.decode_stream_header(
                header,
                auth_key=self.auth_key,
                max_payload=framing.MAX_FRAME,
                direction="down",
            )
        )
        if self.auth_key is not None and (
            meta.get("role") != "server" or meta.get("nonce") != nonce_hex
        ):
            # Checked BEFORE any model bytes move (the dense path checks
            # after its one-frame decode; here the meta arrives first).
            raise wire.WireError(
                "streamed reply failed the freshness check (stale nonce "
                "or wrong role) — possible replay"
            )
        nonce = bytes.fromhex(nonce_hex) if nonce_hex else b""
        sink = self.reply_leaf_sink
        if self.dp or self._topk_frac is not None or (
            meta.get("dp_reply") is not None
        ):
            # DP deltas and sparse bases need host arithmetic before any
            # placement; the sink contract is absolute aggregate leaves.
            sink = None
        flat: dict[str, Any] = {}
        ti = 0
        leaf_buf = bytearray()
        wire_prof = self._armed_wire_profiler()

        def _consume(data) -> None:
            nonlocal ti, leaf_buf
            off = 0
            while True:
                while ti < len(tensors) and len(leaf_buf) == int(
                    tensors[ti]["nbytes"]
                ):
                    t = tensors[ti]
                    sampled = (
                        wire_prof.tick() if wire_prof is not None else False
                    )
                    td0 = time.monotonic() if sampled else 0.0
                    arr = wire.decode_tensor_entry(t, bytes(leaf_buf))
                    flat[t["key"]] = sink(t["key"], arr) if sink else arr
                    if sampled:
                        # One unpack "step" = one leaf's decode + (on a
                        # meshed client) on-device placement.
                        wire_prof.note("wire", time.monotonic() - td0)
                    leaf_buf = bytearray()
                    ti += 1
                if off >= len(data):
                    return
                if ti >= len(tensors):
                    raise wire.WireError(
                        "reply stream carries bytes past its last tensor"
                    )
                take = min(
                    int(tensors[ti]["nbytes"]) - len(leaf_buf),
                    len(data) - off,
                )
                leaf_buf += data[off : off + take]
                off += take

        received = 0
        seq = 0
        got = len(header)
        _consume(b"")  # zero-size leading leaves / empty payloads
        while received < payload_nbytes:
            frame = framing.recv_frame(sock, send_ack=False)
            got += len(frame)
            data = wire.decode_stream_chunk(
                frame,
                expect_seq=seq,
                auth_key=self.auth_key,
                nonce=nonce,
                direction="down",
            )
            if not data:
                raise wire.WireError(f"empty reply stream chunk (seq {seq})")
            seq += 1
            if received + len(data) > payload_nbytes:
                raise wire.WireError(
                    "reply stream overruns its declared payload size"
                )
            received += len(data)
            _consume(data)
        if ti != len(tensors) or leaf_buf:
            raise wire.WireError("reply stream ended mid-tensor")
        trailer = framing.recv_frame(sock)
        got += len(trailer)
        wire.decode_stream_end(
            trailer,
            expect_chunks=seq,
            auth_key=self.auth_key,
            nonce=nonce,
            direction="down",
        )
        return (
            flat, meta, got,
            wire_prof.span_attrs() if wire_prof is not None else {},
        )

    # ------------------------------------------------------ observability
    def note_local_phase(
        self, t_start: float, dur_s: float, **attrs
    ) -> None:
        """Buffer a ``client-local`` span measured by the caller (the CLI
        round loop times local training BEFORE the exchange). It is
        written on the next successful exchange, once the reply meta
        reveals the round's trace id — the identity a client cannot know
        while it is still training."""
        self.note_phase("client-local", t_start, dur_s, **attrs)

    def note_phase(
        self, name: str, t_start: float, dur_s: float, **attrs
    ) -> None:
        """Buffer an arbitrary caller-measured span (``client-local``,
        ``batch-prefetch``, ...) for the next successful exchange, which
        stamps it with the round's (trace, round) identity."""
        self._pending_spans.append(
            (str(name), float(t_start), float(dur_s), dict(attrs))
        )

    def _flush_spans(
        self,
        agg_meta: Mapping[str, Any],
        upload: tuple[float, float, int, dict | None] | None,
        reply: tuple[float, float, int, dict | None] | None,
    ) -> None:
        """Adopt the reply's (trace, round) identity and write this
        round's spans: buffered client-local phases first (they happened
        first), then wire-upload and wire-reply."""
        trace = agg_meta.get("trace")
        rnd = agg_meta.get("agg_round")
        try:
            rnd = int(rnd) if rnd is not None else None
        except (TypeError, ValueError):
            rnd = None
        self.last_trace = (trace if isinstance(trace, str) else None, rnd)
        trace = self.last_trace[0]
        if self.tracer is None:
            self._pending_spans.clear()
            return
        for name, t_start, dur_s, attrs in self._pending_spans:
            self.tracer.record(
                name, t_start=t_start, dur_s=dur_s, trace=trace,
                round=rnd, **attrs,
            )
        self._pending_spans.clear()
        if upload is not None:
            self.tracer.record(
                "wire-upload",
                t_start=upload[0],
                dur_s=upload[1],
                trace=trace,
                round=rnd,
                bytes=upload[2],
                **(upload[3] or {}),
            )
        if reply is not None:
            self.tracer.record(
                "wire-reply",
                t_start=reply[0],
                dur_s=reply[1],
                trace=trace,
                round=rnd,
                bytes=reply[2],
                **(reply[3] if len(reply) > 3 and reply[3] else {}),
            )

    # ------------------------------------------------- sparse round deltas
    def _prepare_topk_upload(
        self, params: Any, attempt: int, attempt_meta: dict
    ) -> tuple[Any, str, dict | None, dict | None]:
        """Choose this attempt's upload form in topk mode.

        Returns ``(upload, compression, delta_flat, sent_flat)``. Sparse
        needs a shared base: round 1 (no aggregate yet), a server that
        never echoed an ``agg_round``, or any retry after a failed attempt
        (the failure may have been the server rejecting a stale base, e.g.
        after a restart — dense is always correct, so retries pay the full
        payload rather than risk a doomed round) all fall back to dense."""
        use_sparse = (
            attempt == 1 and self._base is not None and self._base_round is not None
        )
        flatp = wire.flatten_params(params)
        if use_sparse and not wire.shapes_compatible(flatp, self._base):
            # A changed architecture (keys OR same-key shapes) can't be
            # expressed as a delta; dense is always correct, so fall back
            # instead of crashing on the subtraction or burning a retry.
            log.warning(
                f"[CLIENT {self.client_id}] param key set or shapes changed "
                "since the last aggregate — uploading dense this round"
            )
            use_sparse = False
        if not use_sparse:
            # wants_delta tells the server a delta-capable client is in the
            # round, so the reply carries the agg_crc base-agreement stamp
            # even though THIS upload went dense (the stamp is what lets
            # the next round go sparse). A client that has given up on
            # sparse mode (lossy reply compression or a pre-delta server)
            # mostly stops asking — the server shouldn't pay a full-model
            # crc pass every round for a stamp nobody uses — but probes
            # again every PROBE_EVERY rounds so a server that became
            # lossless is rediscovered.
            if self._gave_up_delta:
                # Counted once per ROUND (attempt 1), not per retry: a
                # transient failure must neither consume a probe before
                # the server saw it nor skew the PROBE_EVERY cadence.
                if attempt == 1:
                    self._probe_this_round = (
                        self._dense_rounds_since_giveup % self.PROBE_EVERY == 0
                    )
                    self._dense_rounds_since_giveup += 1
                attempt_meta.update(
                    delta=False, wants_delta=self._probe_this_round
                )
            else:
                attempt_meta.update(delta=False, wants_delta=True)
            return params, "none", None, None
        # A residual accumulated before an architecture change (or carried
        # across a dense-fallback round) is only usable if it still matches
        # the current tensor set/shapes.
        residual = self._residual
        if residual is not None and not wire.shapes_compatible(residual, flatp):
            residual = self._residual = None
        delta: dict[str, np.ndarray] = {}
        sent: dict[str, np.ndarray] = {}
        upload: dict[str, wire.PreEncoded] = {}
        for k, v in flatp.items():
            d = np.asarray(v, np.float32) - self._base[k]
            if residual is not None:
                d = d + residual[k]
            delta[k] = d
            # One top-k selection per tensor: the payload goes to the wire
            # as-is (PreEncoded), and its densified mirror feeds the
            # residual — no second argpartition inside encode.
            buf = wire.sparsify_topk(d, self._topk_frac)
            sent[k] = wire.densify_topk(buf, d.shape)
            upload[k] = wire.PreEncoded("topk", buf, d.shape)
        attempt_meta.update(delta=True, base_agg_round=self._base_round)
        return upload, "none", delta, sent

    def _finish_topk(
        self, agg: dict, agg_meta: Mapping[str, Any], delta_flat, sent_flat
    ) -> None:
        """Post-round bookkeeping: adopt the new aggregate as the next
        round's delta base and fold this round's dropped mass into the
        error-feedback residual.

        A round that went dense (retry fallback, fresh base, key-set
        change) RETAINS the residual: the dense upload shipped the current
        params exactly, but the residual holds drift from *earlier* local
        training that was dropped by top-k and then discarded when the
        client adopted the aggregate — mass the module's contract promises
        is "carried to the next round, never lost". The next sparse
        delta (params - base + residual) remains correct. It is cleared
        only when the base is abandoned (lossy-base refusal below) or no
        longer shape-compatible (_prepare_topk_upload)."""
        if delta_flat is not None:
            self._residual = {
                k: delta_flat[k] - sent_flat[k] for k in delta_flat
            }
        agg_round = agg_meta.get("agg_round")
        if agg_round is None:
            # Server without delta support: stay dense (probe occasionally).
            self._base = self._base_round = None
            if not self._gave_up_delta:
                self._gave_up_delta = True
                self._dense_rounds_since_giveup = 1
            return
        base = {
            k: np.asarray(v, np.float32)
            for k, v in wire.flatten_params(agg).items()
        }
        # Base-agreement contract: only adopt the reply as a delta base if
        # it is bit-identical to the server's fp32 aggregate (the stamped
        # crc). A lossy reply compression (serve --compression bf16/int8)
        # would otherwise make every later sparse round reconstruct
        # against a base the server doesn't hold, silently biasing the
        # model by the base's quantization error.
        try:
            matches = wire.flat_crc32(base) == int(agg_meta["agg_crc"])
        except (KeyError, TypeError, ValueError):
            matches = False
        if not matches:
            if not self._warned_lossy_base:
                self._warned_lossy_base = True
                log.warning(
                    f"[CLIENT {self.client_id}] reply aggregate does not "
                    "match the server's exact fp32 base (lossy reply "
                    "compression, or a pre-delta server) — uploads stay "
                    "dense"
                )
            self._base = self._base_round = self._residual = None
            if not self._gave_up_delta:
                self._gave_up_delta = True
                self._dense_rounds_since_giveup = 1
            return
        self._base = base
        self._base_round = int(agg_round)
        # A matching base (possibly via a recovery probe) re-arms sparse mode.
        self._gave_up_delta = False

    def _parse_keys_frame(
        self, frame: bytes, priv: int, session: bytes, round_no: int
    ) -> tuple[list[int], dict[int, bytes]]:
        """KEYS frame -> (sorted participant ids, {partner id: DH pair
        secret}). Validates the magic, every public value, and (in auth
        mode) each key's HMAC binding to (session, round, owner id). The
        set may be a quorum SUBSET of the fleet (the server closes the key
        set after its grace window when clients die before the exchange);
        it must contain this client, at least ``min_participants`` members
        (default: the full fleet — the client-side floor that stops a
        compromised server or MITM from shrinking a client's mask-partner
        set to a colluding singleton), and only known ids. Masking over a
        set meeting the operator's floor is as safe as the full fleet
        against the module's threat model; refusing a smaller one raises
        :class:`~.secure.SecureAggError`, which ``exchange`` does NOT
        retry (a downgraded advert would repeat identically)."""
        import struct as _struct

        entry = 8 + secure.DH_PUB_LEN + (
            wire.AUTH_TAG_LEN if self.auth_key is not None else 0
        )
        n_magic = len(wire.KEYS_MAGIC)
        if not frame.startswith(wire.KEYS_MAGIC) or (
            (len(frame) - n_magic) % entry != 0
        ):
            raise wire.WireError("bad DH keys frame from server")
        seen: dict[int, bytes] = {}
        for off in range(n_magic, len(frame), entry):
            cid = _struct.unpack("<q", frame[off : off + 8])[0]
            if cid in seen:
                raise wire.WireError(f"duplicate client {cid} in keys frame")
            pub = frame[off + 8 : off + 8 + secure.DH_PUB_LEN]
            if self.auth_key is not None:
                secure.verify_pubkey_tag(
                    self.auth_key, session, round_no, cid, pub,
                    frame[off + 8 + secure.DH_PUB_LEN : off + entry],
                )
            seen[cid] = pub
        participants = sorted(seen)
        if not all(0 <= c < self.num_clients for c in participants):
            raise wire.WireError(
                f"DH keys frame covers unknown clients {participants} "
                f"(fleet is 0..{self.num_clients - 1})"
            )
        if self.client_id not in seen or len(seen) < 2:
            raise wire.WireError(
                f"DH keys frame covers {participants}: it must include "
                f"this client ({self.client_id}) and at least one partner"
            )
        if len(seen) < self.min_participants:
            # Fail closed and non-retryably: below the operator's floor the
            # set may have been shrunk to colluders (downgrade attack), and
            # a retry would receive the same set.
            raise secure.SecureAggError(
                f"DH keys frame covers only {len(seen)} participants "
                f"{participants}; this client's floor is "
                f"min_participants={self.min_participants} — refusing the "
                "downgraded set (pass min_participants to opt into "
                "dropout-recovery quorums)"
            )
        return participants, {
            cid: secure.dh_pair_secret(priv, pub)
            for cid, pub in seen.items()
            if cid != self.client_id
        }

    def _double_share_exchange(
        self,
        sock,
        participants: list[int],
        pair_secrets: dict[int, bytes],
        sk_seed: bytes,
        session: bytes,
        round_no: int,
    ) -> dict:
        """Double-masking share distribution: deal Shamir shares of this
        client's self-mask seed and DH key seed to the keyed participants
        (encrypted per holder under the pair secret), send them through
        the server, and adopt the relayed share-complete set U2 as the
        round's mask set. Returns the per-round share state (cached so
        RETRIES resend byte-identical shares — the server enforces
        first-deal-wins)."""
        from . import shamir

        t = (
            self.secure_threshold
            if self.secure_threshold is not None
            else secure.majority_threshold(len(participants))
        )
        if not 2 <= t <= len(participants):
            raise secure.SecureAggError(
                f"Shamir threshold {t} infeasible for "
                f"{len(participants)} participants"
            )
        key = (session, round_no)
        st = self._round_shares.get(key)
        if st is not None and (
            st["participants"] != list(participants) or st["t"] != t
        ):
            # The keyed set is fixed once distributed; a different set on
            # a retry means the server is playing games — fail closed.
            raise secure.SecureAggError(
                "keyed participant set changed across retries of one round"
            )
        if st is None:
            b_seed = os.urandom(secure.SEED_LEN)
            xs = [secure.share_x(p) for p in participants]
            shares_b = shamir.split(b_seed, xs, t)
            shares_sk = shamir.split(sk_seed, xs, t)
            blobs = {
                p: secure.encrypt_share_blob(
                    pair_secrets[p], session, round_no,
                    self.client_id, p,
                    shares_b[secure.share_x(p)],
                    shares_sk[secure.share_x(p)],
                )
                for p in participants
                if p != self.client_id
            }
            st = {
                "participants": list(participants),
                "t": t,
                "b_seed": b_seed,
                "own_b_share": shares_b[secure.share_x(self.client_id)],
                "commit": secure.b_seed_commitment(
                    b_seed, session, round_no, self.client_id
                ),
                "blobs": blobs,
            }
            self._round_shares[key] = st
        framing.send_frame(
            sock,
            secure.build_shares_frame(
                self.client_id,
                st["commit"],
                st["blobs"],
                threshold=t,
                session=session,
                round_index=round_no,
                auth_key=(
                    self._identity_key if self.auth_key is not None else None
                ),
            ),
        )
        u2, entries = secure.parse_shareset_frame(
            framing.recv_frame(sock),
            session=session,
            round_index=round_no,
            auth_key=(
                self._identity_key if self.auth_key is not None else None
            ),
        )
        u2_sorted = sorted(u2)
        u2set = set(u2_sorted)
        if self.client_id not in u2set:
            raise secure.SecureAggError(
                f"share-complete set {u2_sorted} excludes this client"
            )
        if not u2set.issubset(set(participants)):
            raise wire.WireError(
                f"shareset U2 {u2_sorted} is not a subset of the keyed "
                f"participants {sorted(participants)}"
            )
        if len(u2_sorted) < self.min_participants:
            raise secure.SecureAggError(
                f"share-complete set covers only {len(u2_sorted)} "
                f"participants {u2_sorted}; this client's floor is "
                f"min_participants={self.min_participants} — refusing the "
                "downgraded set"
            )
        if len(u2_sorted) < t:
            # Fewer dealers than the Shamir threshold could never unmask:
            # masking and uploading into such a round is wasted work that
            # ends in a guaranteed server-side failure.
            raise secure.SecureAggError(
                f"share-complete set {u2_sorted} is smaller than the "
                f"Shamir threshold {t} — the round could never unmask"
            )
        if set(entries) != u2set - {self.client_id}:
            raise wire.WireError(
                f"shareset entries cover dealers {sorted(entries)}, "
                f"expected {sorted(u2set - {self.client_id})}"
            )
        holder_shares = {}
        for dealer, blob in entries.items():
            holder_shares[dealer] = secure.decrypt_share_blob(
                pair_secrets[dealer], session, round_no,
                dealer, self.client_id, blob,
            )
        # Pin U2 and the decrypted holder shares across retries of one
        # round, exactly as ``participants`` is pinned above: the
        # share-complete set is fixed once relayed. A retried connection
        # relaying a DIFFERENT set (or different dealer shares) is the
        # server steering this client between mask partitions to
        # difference its uploads — fail closed, no retry (SecureAggError
        # propagates past the retry loop).
        if "u2" in st:
            if st["u2"] != u2_sorted:
                raise secure.SecureAggError(
                    "share-complete set changed across retries of one "
                    f"round (pinned {st['u2']}, relayed {u2_sorted}) — "
                    "refusing the substituted shareset"
                )
            if st["holder_shares"] != holder_shares:
                changed = sorted(
                    d
                    for d in holder_shares
                    if st["holder_shares"].get(d) != holder_shares[d]
                )
                raise secure.SecureAggError(
                    f"dealers {changed} re-dealt different shares on a "
                    "retry of one round (U2 unchanged) — refusing the "
                    "substituted shareset"
                )
        st["u2"] = u2_sorted
        st["holder_shares"] = holder_shares
        return st

    def _answer_unmask(
        self, sock, request: bytes, share_st: dict, session: bytes,
        round_no: int,
    ) -> bytes:
        """Validate an unmask request against this round's U2, answer with
        the either/or share set, and return the next (final) reply frame."""
        alive, dead = secure.parse_unmask_request(
            request,
            session=session,
            round_index=round_no,
            auth_key=self._identity_key,
        )
        u2set = set(share_st["u2"])
        if self.client_id not in alive:
            raise secure.SecureAggError(
                "unmask request claims this client did not contribute — "
                "refusing (it would expose our self-mask while the server "
                "holds our upload)"
            )
        if set(alive) | set(dead) != u2set:
            raise secure.SecureAggError(
                f"unmask request partition alive={sorted(alive)} / "
                f"dead={sorted(dead)} does not cover this round's "
                f"participant set {sorted(u2set)} exactly"
            )
        # Pin the FIRST answered (alive, dead) partition for this
        # (session, round): answering a second, different partition would
        # hand the server both kinds of shares for the ids it moved
        # between the sets (answer alive -> b-shares, drop the
        # connection, retry claiming dead -> key-seed shares), re-opening
        # exactly the false-death attack the either/or rule closes.
        # SecureAggError is non-retryable (the exchange retry loop only
        # catches connection/wire errors), so one conflicting request
        # ends the round for this client.
        partition = (tuple(sorted(alive)), tuple(sorted(dead)))
        pinned = share_st.get("unmask_partition")
        if pinned is not None and pinned != partition:
            raise secure.SecureAggError(
                "unmask request partition changed across retries of one "
                f"round (answered alive={list(pinned[0])}/"
                f"dead={list(pinned[1])}, now asked alive={sorted(alive)}/"
                f"dead={sorted(dead)}) — refusing the replayed unmask "
                "(answer-then-drop share harvest)"
            )
        share_st["unmask_partition"] = partition
        holder = share_st["holder_shares"]
        b_shares = {
            d: (
                share_st["own_b_share"]
                if d == self.client_id
                else holder[d][0]
            )
            for d in alive
        }
        sk_shares = {d: holder[d][1] for d in dead}
        framing.send_frame(
            sock,
            secure.build_unmask_response(
                b_shares,
                sk_shares,
                session=session,
                round_index=round_no,
                client_id=self.client_id,
                auth_key=self._identity_key,
            ),
        )
        return framing.recv_frame(sock)
