"""Hierarchical fold tree: the ``fedtpu relay`` intermediate aggregator.

PR 5 made the server's aggregation state O(model + in-flight) and PR 7
made the reply fan-out symmetric (streamed both ways) — but one process
still terminated every client connection, which is the real ceiling on
cohort size (the Smart NIC FL-server study, arXiv:2307.06561, names the
server datapath as the fleet-scale bottleneck; the communication survey,
arXiv:2405.20431, frames hierarchical aggregation as the standard way
past it). A relay terminates a SUBTREE of client connections, folds them
into a partial weighted mean with the same streaming machinery the root
uses (comm/stream_agg.py — leaves fold as chunks land), and forwards ONE
streamed upload to its parent. The root then terminates ``n_relays``
connections instead of ``n_clients``: a 256-client cohort at depth 2
with fanout 16 is 16 connections per process, every hop streamed.

Composition over invention: a relay IS an :class:`~.server.
AggregationServer` (subtree-facing — auth, streamed uploads, eager
folds, obs spans, all unchanged) plus a :class:`~.client.
FederatedClient` (parent-facing — streamed upload up, streamed reply
down), glued by the server's ``reply_via`` hook: between aggregation and
the reply fan-out, the subtree partial goes up, and the ROOT's aggregate
comes back down to be fanned out to the subtree's clients. Clients
cannot tell a relay from a root server — same wire protocol, same
capability adverts, same retries.

Weight contract (what makes the tree a mean, not an artifact of its
shape): the relay's subtree mean is ALWAYS sample-count weighted, and
its upward upload carries ``n_samples = sum(subtree n_samples)``; run
the ROOT with ``--weighted`` so subtree means recombine by their true
mass. With uniform counts this degrades to the uniform mean exactly.

Bit-exactness contract (the PR 5/6 A/B contract, generalized): every
fold in the tree is individually crc-pinned bit-exact against
``aggregate_flat`` over its own inputs — the relay's partial vs the
barrier mean of its subtree's uploads, the root's aggregate vs the
barrier mean of the relay partials — so the depth-2 result equals
:func:`aggregate_tree` (the pinned order: ascending client id within a
subtree, fixed subtree order at the root) BIT-EXACTLY, replayable from
captured uploads. The depth-2 result differs from the single-process
``aggregate_flat`` over all N clients by fp32 reduction-ORDER ulps only
(fp32 addition is non-associative; same class of divergence as the
data-parallel client's gradient-reduction note in train/client_mesh.py)
— below every metric's resolution, and exactly reproducible from the
pinned order.

Out of scope by design (ROADMAP residuals): secure aggregation stays
single-aggregator (the unmask protocol needs one process holding the
full contributor set) and central DP stays at the root (a subtree
partial forwarded pre-noise would be an un-noised release).
"""

from __future__ import annotations

import time

import numpy as np

from ..utils.logging import get_logger
from . import wire
from .client import FederatedClient
from .server import AggregationServer, aggregate_flat

log = get_logger()


def aggregate_tree(
    models: list[dict[str, np.ndarray]],
    weights: list[float] | None,
    groups: list,
) -> dict[str, np.ndarray]:
    """The fold tree's pinned arithmetic, replayed flat: per group (a
    subtree, indices into ``models`` in ascending client-id order) the
    weighted barrier mean, then the barrier mean of the partials
    weighted by each group's weight mass — exactly the fp32 ops, in
    exactly the order, the relay tier performs. The A/B harnesses
    (tests/test_fleet.py, bench.py fleet) pin the live depth-2 root
    aggregate against this crc-bit-exactly.

    ``groups`` may nest to ANY depth: an element that is itself a list
    is a deeper subtree (a relay whose parent is another relay — the
    wire composes, and this is its replay). Each subtree folds bottom-up
    to a (weighted mean, weight mass) pair; the parent folds child
    partials weighted by their masses. The classic depth-2 call shape
    (``[[0, 1], [2, 3]]``) takes exactly the code path — and produces
    exactly the fp32 ops in exactly the order — it always did."""
    if not isinstance(groups, list) or not groups:
        raise ValueError("aggregate_tree needs non-empty groups")

    def _fold(node) -> tuple[dict[str, np.ndarray], float]:
        if isinstance(node, (int, np.integer)):
            w = 1.0 if weights is None else float(weights[node])
            return models[node], w
        if not isinstance(node, list) or not node:
            raise ValueError("aggregate_tree needs non-empty groups")
        parts: list[dict[str, np.ndarray]] = []
        masses: list[float] = []
        for child in node:
            part, mass = _fold(child)
            parts.append(part)
            masses.append(mass)
        return aggregate_flat(parts, masses), sum(masses)

    agg, _mass = _fold(groups)
    return agg


class RelayAggregator:
    """One ``fedtpu relay`` process: subtree-facing AggregationServer +
    parent-facing FederatedClient, joined by the server's ``reply_via``
    hook.

    ``relay_id`` is this relay's client id on the PARENT's tier (the
    fixed subtree order at the root: relays fold in ascending relay id,
    exactly as clients fold in ascending client id within the subtree).
    ``num_clients`` is the SUBTREE size — the ids this relay terminates
    are whatever its clients present, validated by the same rules as any
    server's.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        parent_host: str,
        parent_port: int,
        relay_id: int,
        num_clients: int,
        min_clients: int | None = None,
        timeout: float = 300.0,
        compression: str = "none",
        auth_key: bytes | None = None,
        stream_chunk_bytes: int = wire.DEFAULT_STREAM_CHUNK,
        stream: bool = True,
        subtree_deadline_factor: float = 0.5,
        tracer=None,
        strategy: str = "fedavg",
        upward_topk: float | None = None,
    ):
        # Sparse upward hops (--upward-topk): the relay's parent-facing
        # leg runs the existing sparse round-delta machinery — its
        # upward upload becomes topk(subtree partial - last root
        # aggregate it fanned down, + error-feedback residual), with
        # base agreement pinned by the root's agg_crc stamp exactly as
        # for a leaf client. The subtree partial drifts by one round's
        # client training, so the upward delta is small even when every
        # leaf uploads dense — upward bytes drop superlinearly with
        # depth (each tier re-sparsifies its own partial). Round 1 (and
        # any round after a base refusal) ships dense automatically; a
        # root running lossy reply compression never confirms a base,
        # so the relay stays dense rather than diverging.
        if upward_topk is not None:
            if compression.startswith("topk"):
                raise ValueError(
                    "upward_topk composes the relay's own upward "
                    "sparsifier; give the subtree-facing --compression "
                    "a non-topk value"
                )
            # Range validation lives in wire.parse_compression.
            wire.parse_compression(f"topk:{float(upward_topk)}")
        # Per-subtree straggler deadline, STRICTLY tighter than the
        # round budget (config.py FedConfig validates the same bound):
        # a slow subtree sheds its stragglers at factor * timeout — run
        # this relay with --min-clients below the subtree size to
        # proceed over survivors — instead of stalling the root until
        # ITS deadline. factor >= 1 would re-create exactly the failure
        # mode this tier exists to remove, so it is refused.
        if not 0.0 < float(subtree_deadline_factor) < 1.0:
            raise ValueError(
                f"subtree_deadline_factor={subtree_deadline_factor} "
                "must be in (0, 1): the subtree deadline has to be "
                "strictly tighter than the round budget"
            )
        # Sample-count weighting is the relay-tier contract (module
        # docstring): subtree means must recombine at the parent by
        # their true mass, so the subtree fold is always weighted
        # (uniform counts make it the uniform mean bit-exactly —
        # aggregate_flat normalizes ones and explicit equal weights to
        # identical float64 values).
        self.server = AggregationServer(
            host,
            port,
            num_clients=num_clients,
            weighted=True,
            min_clients=min_clients,
            timeout=timeout,
            compression=compression,
            auth_key=auth_key,
            stream_chunk_bytes=stream_chunk_bytes,
            tracer=tracer,
        )
        self.parent = FederatedClient(
            parent_host,
            parent_port,
            client_id=relay_id,
            timeout=timeout,
            compression=(
                f"topk:{float(upward_topk)}"
                if upward_topk is not None
                else compression
            ),
            auth_key=auth_key,
            stream=stream,
            tracer=tracer,
        )
        self.upward_topk = (
            float(upward_topk) if upward_topk is not None else None
        )
        #: Cumulative parent-facing upload payload bytes (the
        #: ``relay_upward_bytes`` bench headline / /metrics counter):
        #: what the sparse upward tier exists to shrink.
        self.upward_bytes = 0
        from ..obs import metrics as _obs_metrics

        self._m_upward_bytes = _obs_metrics.default_registry().counter(
            "fedtpu_relay_upward_bytes_total",
            help="parent-facing upload payload bytes shipped by this "
            "relay (sparse upward deltas shrink this, not the subtree "
            "tier's receive totals)",
        )
        self.relay_id = int(relay_id)
        self.subtree_deadline_factor = float(subtree_deadline_factor)
        self.tracer = tracer
        # Strategy agreement stamp (strategies/, wire.STRATEGY_META_KEY):
        # strategies apply at the ROOT only — a subtree partial is not a
        # global, so the relay's own fold never transforms — but the
        # relay declares which strategy it believes the fleet runs on
        # every upward upload, and the root refuses a mismatch (a
        # split-brain fleet folding under two aggregation rules). The
        # declaration is validated here so a typo'd --strategy fails at
        # relay start, not at the root's round.
        from .. import strategies as _strategies

        self.strategy_name = _strategies.make_strategy(strategy).name
        self.server.reply_via = self._forward
        self.port = self.server.port

    # ------------------------------------------------------------ rounds
    def _forward(self, agg: dict, info: dict) -> dict:
        """The ``reply_via`` hook: ship the subtree partial (with its
        aggregate sample mass) to the parent, return the root aggregate
        the subtree's clients will receive. Emits the ``relay-forward``
        span — the upward exchange window, the tree tier's line on the
        obs timeline."""
        total = sum(info["n_samples"].values())
        # fedtpu: allow(determinism): span wall-clock timestamp — feeds the
        # obs timeline only, never the fold value or order
        t_unix = time.time()
        t0 = time.monotonic()
        out = self.parent.exchange(
            agg,
            n_samples=max(1, int(round(total))),
            # Contributor record for the parent's assignment ledger
            # (wire.SUBTREE_IDS_META_KEY): the ascending client ids this
            # partial folded — how the root replays (and crc-pins) the
            # round's ACTUAL tree, re-homed adoptions included, and how
            # it detects a double-counted re-homed upload.
            meta={
                wire.SUBTREE_IDS_META_KEY: [int(i) for i in info["ids"]],
                # Strategy agreement: the root WireErrors this upload if
                # its active strategy id differs (split-brain guard).
                wire.STRATEGY_META_KEY: {"name": self.strategy_name},
            },
        )
        dur = time.monotonic() - t0
        up_bytes = int(self.parent.last_upload_bytes)
        self.upward_bytes += up_bytes
        self._m_upward_bytes.inc(float(up_bytes))
        if self.tracer is not None:
            parent_trace, parent_round = self.parent.last_trace
            self.tracer.record(
                "relay-forward",
                t_start=t_unix,
                dur_s=dur,
                trace=info.get("trace"),
                round=info.get("round"),
                relay=self.relay_id,
                subtree_clients=len(info["ids"]),
                parent_trace=parent_trace,
                parent_round=parent_round,
                # Wire-efficiency attribution: what the upward hop
                # actually cost, and whether it went sparse/quantized.
                upward_bytes=up_bytes,
                upward_sparse=1 if self.upward_topk is not None else None,
                wire_dtype=self.parent.last_wire_dtype,
            )
        log.info(
            f"[RELAY {self.relay_id}] forwarded subtree partial "
            f"({len(info['ids'])} client(s), mass {total:g}, "
            f"{up_bytes / 1e6:.2f} MB up) and received "
            f"the root aggregate in {dur:.3f}s"
        )
        return wire.flatten_params(out)

    def serve_round(self, **kw) -> dict | None:
        """One relay round: gather + fold the subtree, forward the
        partial, fan the root aggregate out to the subtree's clients.
        Returns the ROOT aggregate (flat).

        The default round deadline is ``subtree_deadline_factor *
        timeout`` — strictly tighter than the round budget, so a slow
        subtree resolves (sheds its stragglers, or fails its local
        quorum) while the root is still accepting the other subtrees'
        uploads, instead of stalling the whole tree."""
        kw.setdefault(
            "deadline",
            self.subtree_deadline_factor * self.server.timeout,
        )
        return self.server.serve_round(**kw)

    def serve(self, rounds: int = 1) -> None:
        """Multi-round loop with the server's keep-going contract: a
        failed round (subtree quorum miss, parent unreachable) is logged
        and the next proceeds, so retrying clients can complete it."""
        for r in range(rounds):
            log.info(f"[RELAY {self.relay_id}] round {r + 1}/{rounds}")
            try:
                self.serve_round()
            except (RuntimeError, ConnectionError, OSError) as e:
                log.info(
                    f"[RELAY {self.relay_id}] round {r + 1} failed: {e}"
                )

    # --------------------------------------------------------- lifecycle
    def close(self) -> None:
        """Tear the relay down PROMPTLY, mid-round included: abort the
        parent-facing exchange first (a forward blocked on the root's
        reply — or in a dial backoff — must not wait out its socket
        timeout), then close the subtree server, which sheds every
        pending child upload as an explicit failure (comm/server.py
        close: shutdown-then-close, the prompt-close discipline). The
        children's dead connections are what trigger their re-homing —
        so this teardown path is the failover plane's latency floor."""
        self.parent.abort()
        self.server.close()

    def __enter__(self) -> "RelayAggregator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
