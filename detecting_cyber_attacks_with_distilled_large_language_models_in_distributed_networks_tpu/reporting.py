"""Reporting: metrics CSVs and evaluation plots.

Capability parity with the reference's L7 reporting layer:

* ``save_metrics`` — one-row CSV with the reference's exact five-column
  schema ``Accuracy,Loss,Precision,Recall,F1-Score`` (reference
  client1.py:339-350), so recorded results stay comparable side-by-side.
* ``plot_evaluation`` — confusion-matrix heatmaps and the local-vs-aggregated
  grouped bar chart (reference client1.py:153-225). The reference also
  *defines* ROC and precision-recall plotters but never calls them
  (client1.py:167-193 — dead code); here they are wired in.

Curve math (ROC, PR, AUC) is pure numpy — no sklearn dependency — and plots
are pure matplotlib on the Agg backend (the reference pulls in seaborn only
for ``sns.heatmap``, client1.py:158). Everything here is host-side: metrics
arrive as plain floats/arrays already finalized from on-device counts
(ops/metrics.py).
"""

from __future__ import annotations

import csv
import os
from typing import Mapping, Sequence

import numpy as np

try:  # pragma: no cover - exercised implicitly by import
    # Figure + FigureCanvasAgg directly: rendering never touches the global
    # pyplot state machine or the host process's chosen backend.
    from matplotlib.backends.backend_agg import FigureCanvasAgg
    from matplotlib.figure import Figure

    HAVE_MATPLOTLIB = True
except Exception:  # matplotlib absent: CSVs still work, plots become no-ops
    HAVE_MATPLOTLIB = False

METRIC_COLUMNS = ("Accuracy", "Loss", "Precision", "Recall", "F1-Score")

DEFAULT_DPI = 300  # the reference's higher-quality client2 setting (client2.py:155)


# --------------------------------------------------------------------- CSV IO
def save_metrics(metrics: Mapping[str, float], filename: str) -> str:
    """One-row CSV in the reference's schema (reference client1.py:339-350)."""
    os.makedirs(os.path.dirname(filename) or ".", exist_ok=True)
    with open(filename, "w", newline="") as f:
        writer = csv.DictWriter(f, fieldnames=list(METRIC_COLUMNS))
        writer.writeheader()
        writer.writerow({k: metrics[k] for k in METRIC_COLUMNS})
    return filename


def load_metrics(filename: str) -> dict[str, float]:
    """Inverse of ``save_metrics`` (also reads the reference's recorded CSVs)."""
    with open(filename, newline="") as f:
        row = next(csv.DictReader(f))
    return {k: float(v) for k, v in row.items()}


#: metrics-JSONL schema tag: lets `fedtpu obs` and the drift monitor
#: merge streams (and reject foreign/obs-span lines) without guessing.
METRICS_SCHEMA = "fedtpu-metrics-v1"


def append_metrics_jsonl(path: str, record: Mapping[str, object]) -> None:
    """Append one structured metrics record as a JSON line.

    The reference's only observability is timestamped prints + one-row CSVs
    (SURVEY.md §5); a JSONL stream is the machine-readable upgrade — one
    self-describing record per (round, client, phase), greppable and
    loadable into pandas (``pd.read_json(path, lines=True)``). Non-scalar
    metric entries (probs/labels arrays) are dropped, not serialized —
    EXCEPT short scalar lists (<= 64 entries, e.g. the serving tier's
    binned ``score_hist`` the drift monitor consumes), which are small by
    construction and stay machine-readable.

    Concurrency contract: the whole line goes down in ONE ``os.write`` on
    an ``O_APPEND`` descriptor (obs.trace.append_jsonl_line). The server
    and serving tiers append from several threads; Python's buffered
    ``open(path, "a").write`` can flush a long line in pieces, and two
    writers' partial flushes interleave into unparseable garbage.
    Every record also carries ``schema`` + ``run_id`` so downstream
    mergers can group one run's streams.
    """
    import json

    from .obs.trace import append_jsonl_line, get_run_id

    def _short_scalar_list(v: object) -> list | None:
        if not isinstance(v, (list, tuple)) or len(v) > 64:
            return None
        out = []
        for x in v:
            if isinstance(x, np.generic):
                x = x.item()
            if isinstance(x, bool) or not isinstance(x, (int, float)):
                return None
            out.append(x)
        return out

    clean = {}
    for k, v in record.items():
        if isinstance(v, (str, int, float, bool, np.generic)) or v is None:
            clean[k] = v.item() if isinstance(v, np.generic) else v
        else:
            lst = _short_scalar_list(v)
            if lst is not None:
                clean[k] = lst
    import time

    clean.setdefault("ts", time.time())
    clean.setdefault("schema", METRICS_SCHEMA)
    clean.setdefault("run_id", get_run_id())
    append_jsonl_line(path, json.dumps(clean))


# ------------------------------------------------------------- curve math
def roc_curve(labels: np.ndarray, probs: np.ndarray):
    """ROC points (fpr, tpr, thresholds), numpy-native.

    Matches sklearn's ``roc_curve(..., drop_intermediate=False)``: thresholds
    descending, curve anchored at (0, 0) with an initial +inf threshold, one
    point per distinct threshold (collinear interior points kept).
    """
    labels = np.asarray(labels).astype(np.int64)
    probs = np.asarray(probs).astype(np.float64)
    order = np.argsort(-probs, kind="stable")
    labels, probs = labels[order], probs[order]
    # Cumulative TP/FP at each distinct-threshold boundary.
    distinct = np.where(np.diff(probs))[0]
    idx = np.concatenate([distinct, [labels.size - 1]])
    tps = np.cumsum(labels)[idx].astype(np.float64)
    fps = (idx + 1) - tps
    tps = np.concatenate([[0.0], tps])
    fps = np.concatenate([[0.0], fps])
    thresholds = np.concatenate([[np.inf], probs[idx]])
    p = max(tps[-1], 1.0)
    n = max(fps[-1], 1.0)
    return fps / n, tps / p, thresholds


def precision_recall_curve(labels: np.ndarray, probs: np.ndarray):
    """PR points (precision, recall, thresholds), sklearn convention:
    recall descending to 0, final point (precision=1, recall=0)."""
    labels = np.asarray(labels).astype(np.int64)
    probs = np.asarray(probs).astype(np.float64)
    order = np.argsort(-probs, kind="stable")
    labels, probs = labels[order], probs[order]
    distinct = np.where(np.diff(probs))[0]
    idx = np.concatenate([distinct, [labels.size - 1]])
    tps = np.cumsum(labels)[idx].astype(np.float64)
    fps = (idx + 1) - tps
    denom = np.maximum(tps + fps, 1.0)
    precision = tps / denom
    recall = tps / max(tps[-1], 1.0)
    # Reverse so recall ascends, then append the (1, 0) anchor.
    precision = np.concatenate([precision[::-1], [1.0]])
    recall = np.concatenate([recall[::-1], [0.0]])
    thresholds = probs[idx][::-1]
    return precision, recall, thresholds


def auc(x: np.ndarray, y: np.ndarray) -> float:
    """Trapezoidal area under a curve sorted by x (sklearn ``auc``)."""
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    order = np.argsort(x, kind="stable")
    trapezoid = getattr(np, "trapezoid", None) or np.trapz  # numpy<2 fallback
    return float(trapezoid(y[order], x[order]))


def average_precision(labels: np.ndarray, probs: np.ndarray) -> float:
    """AP = sum over thresholds of (recall step) * precision."""
    precision, recall, _ = precision_recall_curve(labels, probs)
    # recall ascends then ends with the 0 anchor; integrate the step curve.
    return float(-np.sum(np.diff(recall) * precision[:-1]))


# ---------------------------------------------------------------------- plots
def plot_confusion_matrix(
    cm: np.ndarray,
    title: str,
    path: str,
    *,
    class_names: Sequence[str] = ("Benign", "DDoS"),
    dpi: int = DEFAULT_DPI,
) -> str | None:
    """Annotated heatmap of the 2x2 confusion matrix (reference
    client1.py:157-165, there via seaborn)."""
    if not HAVE_MATPLOTLIB:
        return None
    cm = np.asarray(cm)
    fig, ax = _figure((6, 5))
    im = ax.imshow(cm, cmap="Blues")
    fig.colorbar(im, ax=ax)
    thresh = cm.max() / 2.0 if cm.max() > 0 else 0.5
    for i in range(cm.shape[0]):
        for j in range(cm.shape[1]):
            ax.text(
                j,
                i,
                f"{int(cm[i, j]):d}",
                ha="center",
                va="center",
                color="white" if cm[i, j] > thresh else "black",
            )
    ax.set_xticks(range(len(class_names)), class_names)
    ax.set_yticks(range(len(class_names)), class_names)
    ax.set_xlabel("Predicted")
    ax.set_ylabel("Actual")
    ax.set_title(title)
    fig.tight_layout()
    _save(fig, path, dpi)
    return path


def plot_roc_curve(
    labels: np.ndarray, probs: np.ndarray, title: str, path: str, *, dpi: int = DEFAULT_DPI
) -> str | None:
    """ROC with AUC in the legend (reference client1.py:167-181, dead code
    there — wired in here)."""
    if not HAVE_MATPLOTLIB:
        return None
    fpr, tpr, _ = roc_curve(labels, probs)
    fig, ax = _figure((6, 5))
    ax.plot(fpr, tpr, label=f"ROC (AUC = {auc(fpr, tpr):.4f})")
    ax.plot([0, 1], [0, 1], linestyle="--", color="grey", label="Chance")
    ax.set_xlabel("False Positive Rate")
    ax.set_ylabel("True Positive Rate")
    ax.set_title(title)
    ax.legend(loc="lower right")
    fig.tight_layout()
    _save(fig, path, dpi)
    return path


def plot_precision_recall(
    labels: np.ndarray, probs: np.ndarray, title: str, path: str, *, dpi: int = DEFAULT_DPI
) -> str | None:
    """PR curve with average precision (reference client1.py:183-193, dead
    code there — wired in here)."""
    if not HAVE_MATPLOTLIB:
        return None
    precision, recall, _ = precision_recall_curve(labels, probs)
    ap = float(-np.sum(np.diff(recall) * precision[:-1]))
    fig, ax = _figure((6, 5))
    ax.plot(recall, precision, label=f"PR (AP = {ap:.4f})")
    ax.set_xlabel("Recall")
    ax.set_ylabel("Precision")
    ax.set_title(title)
    ax.legend(loc="lower left")
    fig.tight_layout()
    _save(fig, path, dpi)
    return path


def plot_metrics_comparison(
    local: Mapping[str, float],
    aggregated: Mapping[str, float],
    title: str,
    path: str,
    *,
    dpi: int = DEFAULT_DPI,
    labels: tuple[str, str] = ("Local", "Aggregated"),
) -> str | None:
    """Grouped two-model bar chart over the five metrics (reference
    client1.py:195-218; default labels are its local-vs-aggregated pair, the
    distill CLI passes Teacher/Student). Accuracy is rescaled from percent
    to [0, 1] so all bars share an axis, as the reference does
    (client1.py:199-200)."""
    if not HAVE_MATPLOTLIB:
        return None

    def _values(m: Mapping[str, float]) -> list[float]:
        return [
            float(m[k]) / 100.0 if k == "Accuracy" else float(m[k])
            for k in METRIC_COLUMNS
        ]

    x = np.arange(len(METRIC_COLUMNS))
    width = 0.35
    fig, ax = _figure((9, 5))
    ax.bar(x - width / 2, _values(local), width, label=labels[0])
    ax.bar(x + width / 2, _values(aggregated), width, label=labels[1])
    ax.set_xticks(x, METRIC_COLUMNS)
    ax.set_ylabel("Value (Accuracy scaled to [0,1])")
    ax.set_title(title)
    ax.legend()
    fig.tight_layout()
    _save(fig, path, dpi)
    return path


def plot_evaluation(
    local: Mapping,
    aggregated: Mapping | None,
    output_dir: str,
    *,
    client_id: int = 0,
    dpi: int = DEFAULT_DPI,
) -> list[str]:
    """Full reference plot set for one client (reference client1.py:220-224):
    confusion matrices for local and (if present) aggregated models, the
    comparison bar chart, plus ROC and PR curves when probs are available.

    ``aggregated=None`` reproduces the reference's degraded local-only mode
    (client1.py:405-410). Returns paths of the files written."""
    if not HAVE_MATPLOTLIB:
        return []
    os.makedirs(output_dir, exist_ok=True)
    tag = f"client{client_id}"
    written: list[str] = []

    def _emit(path: str | None) -> None:
        if path:
            written.append(path)

    for kind, m in (("local", local), ("aggregated", aggregated)):
        if m is None:
            continue
        _emit(
            plot_confusion_matrix(
                m["confusion_matrix"],
                f"Client {client_id} {kind.capitalize()} Model Confusion Matrix",
                os.path.join(output_dir, f"{tag}_{kind}_confusion_matrix.png"),
                dpi=dpi,
            )
        )
        if "probs" in m and "labels" in m and len(m["probs"]):
            _emit(
                plot_roc_curve(
                    m["labels"],
                    m["probs"],
                    f"Client {client_id} {kind.capitalize()} Model ROC",
                    os.path.join(output_dir, f"{tag}_{kind}_roc.png"),
                    dpi=dpi,
                )
            )
            _emit(
                plot_precision_recall(
                    m["labels"],
                    m["probs"],
                    f"Client {client_id} {kind.capitalize()} Model Precision-Recall",
                    os.path.join(output_dir, f"{tag}_{kind}_pr.png"),
                    dpi=dpi,
                )
            )
    if aggregated is not None:
        _emit(
            plot_metrics_comparison(
                local,
                aggregated,
                f"Client {client_id} Local vs Aggregated Metrics",
                os.path.join(output_dir, f"{tag}_metrics_comparison.png"),
                dpi=dpi,
            )
        )
    return written


def _figure(figsize: tuple[float, float]):
    fig = Figure(figsize=figsize)
    FigureCanvasAgg(fig)
    return fig, fig.add_subplot()


def _save(fig, path: str, dpi: int) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fig.savefig(path, dpi=dpi)
