"""The ``fedtpu controller`` daemon: continuous eval-gated federated rounds.

One controller cycle::

    trigger (drift verdict | max-interval clock | bootstrap)
      -> serve one TCP round through the EXISTING round engine
         (comm/server.py AggregationServer.serve_round — clients connect
         exactly as they always did; the straggler deadline / quorum /
         retry machinery is reused, not reimplemented)
      -> evaluate the aggregate on the held-out split (eval_fn)
      -> register an immutable candidate artifact (registry/)
      -> eval gate (train/fedeval.eval_gate) vs the serving incumbent
           pass  -> promote candidate -> shadow -> serving
                    (atomic pointer swap; the scoring tier follows it)
           fail  -> reject; the pointer NEVER moves — automatic
                    rollback-by-refusal on regression
      -> feed the promoted artifact's eval histogram to the drift
         monitor as the new reference

Every cycle appends one structured record to the controller-state JSONL;
a restarted controller replays that file to resume mid-campaign (round
counter, promotion/rejection tallies) instead of starting a colliding
round 0. The registry's serving pointer survives restarts by
construction, so the drift reference re-anchors from the registry.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Mapping

import numpy as np

from ..comm import wire
from ..config import ControlConfig
from ..obs import metrics as obs_metrics
from ..registry import ModelRegistry, RegistryError
from ..train.fedeval import eval_gate, reference_histogram
from ..utils.logging import get_logger
from .drift import (
    DriftMonitor,
    ErrorRateMonitor,
    cadence_interval_s,
    drift_cohort_fraction,
)

log = get_logger()


class SloActuator:
    """Health-plane actuation (the first SLO->control rung): tail the
    scrape hub's alerts-JSONL and, WHILE a round-duration burn alert is
    firing, tighten the controller's straggler deadline by a configured
    factor — a fleet already blowing its round SLO should cut stragglers
    loose sooner, not spend the full budget waiting on them. The alert
    clearing restores the configured deadline.

    Pure event arithmetic: no clock reads, no sleeps — state is exactly
    the fire/clear events consumed so far (per (slo, instance), so two
    hubs or two instances can fire independently), which is what makes
    the whole behavior unit-testable from a synthetic alerts file."""

    def __init__(
        self,
        alerts_jsonl: str,
        *,
        slo_name: str = "round-duration",
        factor: float = 0.5,
    ):
        if not 0.0 < float(factor) <= 1.0:
            raise ValueError(
                f"factor={factor} must be in (0, 1] (1 = no tightening)"
            )
        self.alerts_jsonl = alerts_jsonl
        self.slo_name = str(slo_name)
        self.factor = float(factor)
        self._offset = 0
        self._firing: set[str] = set()

    @property
    def firing(self) -> bool:
        return bool(self._firing)

    def poll(self) -> bool:
        """Ingest new alert events; True while the matched SLO fires
        somewhere. Malformed lines are skipped (the alerts file is
        another process's output)."""
        from ..obs.timeline import read_new_jsonl_lines

        self._offset, lines = read_new_jsonl_lines(
            self.alerts_jsonl, self._offset
        )
        for line in lines:
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(ev, dict) or ev.get("slo") != self.slo_name:
                continue
            key = str(ev.get("instance"))
            if ev.get("event") == "fire":
                self._firing.add(key)
            elif ev.get("event") == "clear":
                self._firing.discard(key)
        return self.firing

    def effective_deadline(self, base: float | None) -> float | None:
        """The straggler deadline to hand the round engine: tightened by
        ``factor`` while firing, the configured ``base`` otherwise (a
        None base — server-timeout-governed rounds — stays None; there
        is no number to tighten)."""
        if base is None or not self._firing:
            return base
        return float(base) * self.factor

#: eval_fn contract: nested params dict -> metrics mapping. Must carry the
#: gate metric; a "probs" array (np.ndarray) makes the candidate's eval
#: reference histogram available to the drift monitor.
EvalFn = Callable[[Any], Mapping[str, Any]]


@dataclass
class ControllerStats:
    rounds_attempted: int = 0
    rounds_completed: int = 0
    rounds_failed: int = 0
    promotions: int = 0
    gate_rejections: int = 0
    #: Candidates that passed offline eval but FAILED the live shadow
    #: disagreement gate (shadow/) — rejected with the verdict recorded.
    shadow_rejections: int = 0
    #: Candidates that FAILED the supervised label gate (labels/) —
    #: wrong against delayed ground truth where the incumbent was right.
    label_rejections: int = 0
    drift_triggers: int = 0
    #: round-engine wall seconds (inside serve_round) vs full cycle wall:
    #: the orchestration overhead the bench record reports.
    round_wall_s: float = 0.0
    cycle_wall_s: float = 0.0
    promotion_latency_s: list = field(default_factory=list)


class Controller:
    """Drive ``server`` round after round, gate every candidate, and keep
    the registry's serving pointer on the best evaluated artifact.

    ``server`` is an already-bound :class:`~..comm.AggregationServer`
    (plain or secure-agg; central DP is refused — a DP server only ever
    holds noised mean DELTAS, never the absolute params an artifact
    needs). ``eval_fn`` maps a nested params dict to held-out metrics.
    """

    def __init__(
        self,
        server,
        registry: ModelRegistry,
        eval_fn: EvalFn,
        *,
        control: ControlConfig | None = None,
        state_path: str | None = None,
        drift_monitor: DriftMonitor | None = None,
        model_config: Any | None = None,
        drift_poll_s: float = 1.0,
        tracer=None,
        shadow_gate=None,
        slo_actuator: SloActuator | None = None,
        label_gate=None,
        error_monitor: ErrorRateMonitor | None = None,
        sentinel_link=None,
    ):
        if getattr(server, "dp_clip", 0.0) > 0.0:
            raise ValueError(
                "the controller cannot gate a central-DP server: it never "
                "holds absolute params to register or evaluate (run the DP "
                "tier with its own cadence, or gate on the mesh tier)"
            )
        self.server = server
        self.registry = registry
        self.eval_fn = eval_fn
        self.control = control or ControlConfig()
        self.state_path = state_path
        self.drift = drift_monitor
        self.model_config = model_config
        self.drift_poll_s = float(drift_poll_s)
        # Shadow gate (shadow/gate.py): when set, a candidate that passes
        # offline eval is HELD in the registry shadow state until live
        # mirrored traffic produced a disagreement verdict; regression
        # fails closed to rejected. slo_actuator: the health plane's
        # round-duration alert tightening the straggler deadline.
        self.shadow_gate = shadow_gate
        self.slo_actuator = slo_actuator
        # Label gate (labels/join.py): the SUPERVISED rung after the
        # shadow gate — candidate-vs-serving error over joined delayed
        # ground truth, failing closed below the coverage floor. The
        # error monitor (control/drift.py ErrorRateMonitor) turns the
        # same joined evidence into a drift trigger: the serving model's
        # supervised error rising past its promoted reference fires a
        # corrective round even when score histograms look stable.
        self.label_gate = label_gate
        self.error_monitor = error_monitor
        # Sentinel link (control/drift.py SentinelLink): the tail of the
        # standalone sentinel's verdicts-JSONL — supervised drift the
        # sentinel detected BETWEEN gates, in another process, poking
        # the same corrective-round path the in-process monitor uses.
        self.sentinel_link = sentinel_link
        self.stats = ControllerStats()
        # Drift-scaled cohort: a drift verdict's magnitude picks the
        # NEXT round's quorum between the configured fractions of the
        # server's base min_clients (mild drift -> lean fast cohort,
        # severe drift -> the full quorum's evidence).
        self._base_min_clients: int | None = getattr(
            server, "min_clients", None
        )
        self._cohort_override: int | None = None
        # Adaptive cadence: a drift verdict's magnitude sets the NEXT
        # inter-round throttle (None = the configured min_interval_s).
        self._interval_override: float | None = None
        self._slo_tightened = False
        # Observability (obs/): spans stamped with the round engine's
        # (trace, round) — server.last_trace after each serve_round — so
        # the obs timeline shows eval-gate/promote time next to the
        # round's compute/wait/wire phases; counters feed /metrics.
        self.tracer = tracer
        m = obs_metrics.default_registry()
        self._m_rounds = m.counter(
            "fedtpu_controller_rounds_total",
            help="controller cycles attempted",
        )
        self._m_promotions = m.counter(
            "fedtpu_controller_promotions_total",
            help="candidates promoted to serving",
        )
        self._m_gate_rejections = m.counter(
            "fedtpu_controller_gate_rejections_total",
            help="candidates rejected by the eval gate",
        )
        self._m_shadow_rejections = m.counter(
            "fedtpu_controller_shadow_rejections_total",
            help="candidates rejected by the live shadow disagreement gate",
        )
        self._m_label_rejections = m.counter(
            "fedtpu_controller_label_rejections_total",
            help="candidates rejected by the supervised label gate",
        )
        self._m_drift_triggers = m.counter(
            "fedtpu_controller_drift_triggers_total",
            help="rounds triggered by the drift monitor",
        )
        self._next_round = 0
        self._last_round_start: float | None = None
        if state_path:
            self._resume(state_path)
        if self.drift is not None:
            self._seed_drift_reference()

    # ----------------------------------------------------------------- state
    def _resume(self, path: str) -> None:
        """Replay the controller-state JSONL: round counter + tallies. A
        half-written trailing line (crash mid-append) is skipped."""
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError:
            return
        for line in lines:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            r = rec.get("round")
            if isinstance(r, int):
                self._next_round = max(self._next_round, r + 1)
            ev = rec.get("event")
            # Every cycle writes exactly one of these five records, so
            # the attempted/completed tallies replay exactly (a restarted
            # campaign's summary must stay internally consistent —
            # promotions can never exceed completed rounds).
            if ev in (
                "promoted",
                "gate_rejected",
                "shadow_rejected",
                "label_rejected",
                "promote_noop",
                "round_noop",
                "round_failed",
                "cycle_error",
            ):
                self.stats.rounds_attempted += 1
            if ev in (
                "promoted", "gate_rejected", "shadow_rejected",
                "label_rejected", "promote_noop", "cycle_error",
            ):
                self.stats.rounds_completed += 1
            if ev == "promoted":
                self.stats.promotions += 1
            elif ev == "gate_rejected":
                self.stats.gate_rejections += 1
            elif ev == "shadow_rejected":
                self.stats.shadow_rejections += 1
            elif ev == "label_rejected":
                self.stats.label_rejections += 1
            elif ev == "round_failed":
                self.stats.rounds_failed += 1
            elif ev == "drift_trigger":
                self.stats.drift_triggers += 1
        if self._next_round or self.stats.promotions:
            log.info(
                f"[CONTROLLER] resumed campaign from {path}: next round "
                f"{self._next_round} ({self.stats.promotions} promotion(s), "
                f"{self.stats.gate_rejections} gate rejection(s) so far)"
            )

    def _record(self, event: str, **fields: Any) -> None:
        if not self.state_path:
            return
        os.makedirs(os.path.dirname(self.state_path) or ".", exist_ok=True)
        with open(self.state_path, "a") as f:
            f.write(json.dumps({"ts": time.time(), "event": event, **fields}) + "\n")

    def _seed_drift_reference(self) -> None:
        """Re-anchor the drift reference from whatever is serving (resume
        path: the registry outlives the controller process)."""
        try:
            m = self.registry.serving_manifest()
        except RegistryError:
            return
        if m and m.get("eval_hist"):
            self.drift.set_reference(m["eval_hist"])
            log.info(
                f"[CONTROLLER] drift reference = serving artifact "
                f"{m['id']}'s eval histogram"
            )

    # --------------------------------------------------------------- trigger
    def _wait_for_trigger(self, stop: threading.Event) -> str | None:
        """Block until the next round should run; returns the trigger name
        (``bootstrap`` | ``drift`` | ``interval``) or None when stopped."""
        c = self.control
        # Back-to-back throttle applies to every trigger source.
        if self._last_round_start is not None and c.min_interval_s > 0.0:
            wake = self._last_round_start + c.min_interval_s
            while time.monotonic() < wake:
                if stop.wait(min(0.2, wake - time.monotonic())):
                    return None
        if self.registry.serving_info() is None:
            return "bootstrap"  # nothing serving: a round is needed regardless
        if self.drift is None:
            return "interval"  # fixed cadence (min_interval is the clock)
        if not self.drift.has_reference:
            # Serving artifact without an eval histogram (e.g. published
            # by `federated --registry-dir` and hand-promoted): drift can
            # NEVER fire against nothing — waiting on it would idle the
            # campaign forever. Run a round on the clock instead; its
            # promotion re-anchors the reference and drift takes over.
            log.warning(
                "[CONTROLLER] no drift reference (serving artifact "
                "carries no eval histogram); triggering a round on the "
                "clock so the campaign can re-anchor"
            )
            return "interval"
        start = time.monotonic()
        # Adaptive cadence applies to the CLOCK FALLBACK, not the hard
        # min-interval throttle above: a mild verdict relaxes the next
        # guaranteed round toward max_interval_s, a severe one pulls it
        # toward min_interval_s — while drift keeps being polled the
        # whole time, so a new emergency still fires immediately. The
        # recorded next_interval_s is therefore the true time to the
        # next round absent further drift.
        effective_max = (
            self._interval_override
            if self._interval_override is not None
            else c.max_interval_s
        )
        while True:
            verdict = self.drift.poll()
            if verdict is not None:
                self.stats.drift_triggers += 1
                self._m_drift_triggers.inc()
                # Adaptive cadence: the verdict's MAGNITUDE (for PSI,
                # exactly the psi_contributions total) picks the next
                # inter-round throttle between the configured bounds.
                next_interval = None
                if c.adaptive_cadence:
                    next_interval = cadence_interval_s(
                        verdict["drift"],
                        threshold=self.drift.threshold,
                        min_s=c.min_interval_s,
                        max_s=c.max_interval_s,
                    )
                    self._interval_override = next_interval
                    log.info(
                        f"[CONTROLLER] adaptive cadence: drift "
                        f"{verdict['drift']:.4f} -> next interval "
                        f"{next_interval:.1f}s"
                    )
                # Drift-scaled cohort: the verdict's magnitude picks the
                # corrective round's quorum (applied to the server for
                # ONE round in run_cycle, then restored).
                cohort = None
                if c.drift_cohort and self._base_min_clients:
                    frac = drift_cohort_fraction(
                        verdict["drift"],
                        threshold=self.drift.threshold,
                        min_frac=c.cohort_min_frac,
                        max_frac=c.cohort_max_frac,
                    )
                    base = int(self._base_min_clients)
                    cohort = max(1, min(base, int(round(base * frac))))
                    self._cohort_override = cohort
                    log.info(
                        f"[CONTROLLER] drift-scaled cohort: drift "
                        f"{verdict['drift']:.4f} -> quorum {cohort}/{base} "
                        "for the corrective round"
                    )
                self._record(
                    "drift_trigger",
                    **verdict,
                    **(
                        {"next_interval_s": round(next_interval, 3)}
                        if next_interval is not None
                        else {}
                    ),
                    **(
                        {"cohort_target": cohort}
                        if cohort is not None
                        else {}
                    ),
                )
                if self.tracer is not None:
                    # No (trace, round) yet — the round this verdict
                    # starts hasn't minted one; the round index links
                    # them. top_bins is the PSI localization: WHICH
                    # score region moved (control/drift.py).
                    self.tracer.record(
                        "drift-trigger",
                        t_start=time.time(),
                        dur_s=0.0,
                        round=self._next_round,
                        drift=verdict["drift"],
                        method=verdict["method"],
                        scores=verdict["scores"],
                        top_bins=verdict.get("top_bins"),
                        next_interval_s=(
                            round(next_interval, 3)
                            if next_interval is not None
                            else None
                        ),
                    )
                return "drift"
            if self.error_monitor is not None:
                # Supervised drift: the serving model's error over joined
                # delayed ground truth rising past its promoted reference
                # — the regression score histograms cannot see (the model
                # can be confidently, stably WRONG).
                sup = self.error_monitor.check()
                if sup is not None:
                    self.stats.drift_triggers += 1
                    self._m_drift_triggers.inc()
                    self._record("drift_trigger", **sup)
                    if self.tracer is not None:
                        self.tracer.record(
                            "drift-trigger",
                            t_start=time.time(),
                            dur_s=0.0,
                            round=self._next_round,
                            drift=sup["drift"],
                            method=sup["method"],
                            scores=sup["scores"],
                        )
                    log.info(
                        f"[CONTROLLER] supervised drift: serving error "
                        f"{sup['error']:.4f} vs reference "
                        f"{sup['reference_error']:.4f} over "
                        f"{sup['scores']} joined flow(s)"
                    )
                    return "drift"
            if self.sentinel_link is not None:
                # The standalone sentinel's between-gates verdict, same
                # handling as the in-process monitor — the verdict shape
                # is the ErrorRateMonitor's, journaled cross-process.
                sup = self.sentinel_link.poll()
                if sup is not None:
                    self.stats.drift_triggers += 1
                    self._m_drift_triggers.inc()
                    self._record(
                        "drift_trigger",
                        **{
                            k: sup.get(k)
                            for k in (
                                "drift", "method", "threshold",
                                "scores", "error", "reference_error",
                            )
                        },
                    )
                    if self.tracer is not None:
                        self.tracer.record(
                            "drift-trigger",
                            t_start=time.time(),
                            dur_s=0.0,
                            round=self._next_round,
                            drift=sup["drift"],
                            method=sup["method"],
                            scores=sup.get("scores"),
                        )
                    log.info(
                        f"[CONTROLLER] sentinel drift verdict: error "
                        f"{sup.get('error')} vs reference "
                        f"{sup.get('reference_error')} over "
                        f"{sup.get('scores')} joined flow(s)"
                    )
                    return "drift"
            if (
                effective_max is not None
                and time.monotonic() - start >= effective_max
            ):
                # A clock round means the drift stayed quiet for the
                # whole (possibly adapted) interval: relax the override
                # back to the configured cadence.
                self._interval_override = None
                return "interval"
            if stop.wait(self.drift_poll_s):
                return None

    # ----------------------------------------------------------------- cycle
    def run_cycle(self, trigger: str = "interval") -> dict:
        """One round -> gate -> promote/reject cycle. Returns the cycle's
        state record (also appended to the state JSONL)."""
        c = self.control
        r = self._next_round
        self._next_round += 1
        self._last_round_start = time.monotonic()
        self.stats.rounds_attempted += 1
        self._m_rounds.inc()
        log.info(f"[CONTROLLER] round {r} starting (trigger: {trigger})")
        # SLO-driven actuation: while the health plane's round-duration
        # alert fires, the straggler deadline tightens by the configured
        # factor (and restores the moment the alert clears).
        deadline = c.round_deadline_s
        self._slo_tightened = False
        if self.slo_actuator is not None and self.slo_actuator.poll():
            tightened = self.slo_actuator.effective_deadline(deadline)
            if tightened != deadline:
                self._slo_tightened = True
                log.info(
                    f"[CONTROLLER] round-duration SLO firing: straggler "
                    f"deadline {deadline:.1f}s -> {tightened:.1f}s until "
                    "the alert clears"
                )
                deadline = tightened
        cohort = self._cohort_override
        if cohort is not None and self._base_min_clients:
            # One corrective round at the drift-scaled quorum; the base
            # quorum restores whatever the round's outcome.
            self.server.min_clients = cohort
        try:
            t0 = time.monotonic()
            agg = self.server.serve_round(
                deadline=deadline, round_index=r
            )
            round_wall = time.monotonic() - t0
        except (RuntimeError, OSError, ConnectionError, ValueError) as e:
            # Quorum miss / straggler deadline (RuntimeError), a malformed
            # upload surviving to aggregation (WireError/SecureAggError,
            # both ValueErrors), or a socket error: the campaign continues
            # — one failed round must not kill the daemon (the single most
            # important behavioral difference from the reference server).
            self.stats.rounds_failed += 1
            rec = {"round": r, "trigger": trigger, "error": str(e)}
            self._record("round_failed", **rec)
            log.info(f"[CONTROLLER] round {r} failed: {e}")
            return {"event": "round_failed", **rec}
        finally:
            if cohort is not None and self._base_min_clients:
                self.server.min_clients = int(self._base_min_clients)
                self._cohort_override = None
        self.stats.round_wall_s += round_wall
        if agg is None:
            rec = {"round": r, "trigger": trigger}
            self._record("round_noop", **rec)
            return {"event": "round_noop", **rec}
        self.stats.rounds_completed += 1
        t_end = time.monotonic()
        try:
            return self._gate_and_promote(
                r, trigger, agg, t_end=t_end, round_wall=round_wall
            )
        except Exception as e:
            # Eval of a foreign-architecture aggregate, a full disk under
            # the registry write, any other post-round surprise: the ROUND
            # engine is healthy, so the campaign continues — same
            # one-bad-cycle-must-not-kill-the-daemon contract as above.
            rec = {"round": r, "trigger": trigger, "error": f"{type(e).__name__}: {e}"}
            self._record("cycle_error", **rec)
            log.info(
                f"[CONTROLLER] round {r} completed but its gate/promote "
                f"cycle failed ({type(e).__name__}: {e}); serving pointer "
                "unchanged"
            )
            return {"event": "cycle_error", **rec}

    def _maybe_gc(self) -> None:
        """Registry GC after a promotion/rejection moved the state
        machine (ControlConfig.max_artifacts): prune oldest retired/
        rejected artifacts beyond the budget. A GC failure is logged,
        never fatal — disk hygiene must not fail a healthy round."""
        budget = self.control.max_artifacts
        if budget is None:
            return
        try:
            self.registry.gc(max_artifacts=budget)
        except (OSError, RegistryError) as e:
            log.info(f"[CONTROLLER] registry gc failed (non-fatal): {e}")

    def _gate_and_promote(
        self, r: int, trigger: str, agg: dict, *, t_end: float, round_wall: float
    ) -> dict:
        c = self.control
        # The round engine's (trace, round) identity for this cycle's
        # follow-on spans (server.last_trace is set by serve_round).
        trace, _ = getattr(self.server, "last_trace", None) or (None, None)
        nested = wire.unflatten_params(agg)
        t_gate_unix = time.time()
        t_gate0 = time.monotonic()
        metrics = dict(self.eval_fn(nested))
        probs = metrics.pop("probs", None)
        metrics.pop("labels", None)
        eval_hist = (
            reference_histogram(probs, bins=c.score_bins)
            if probs is not None
            else None
        )
        incumbent = self.registry.serving_manifest()
        aid = self.registry.add(
            agg,
            round_index=r,
            metrics=metrics,
            eval_hist=eval_hist,
            model_config=self.model_config,
            parent=incumbent["id"] if incumbent else None,
        )
        if incumbent is not None and aid == incumbent["id"]:
            # Content-addressed dedup: this round's aggregate is
            # bit-identical to what already serves. Short-circuit BEFORE
            # any state transition — promote(to='shadow') would demote
            # the serving artifact's manifest just to fail the final swap.
            rec = {"round": r, "trigger": trigger, "artifact": aid}
            self._record("promote_noop", **rec)
            log.info(
                f"[CONTROLLER] round {r}: aggregate identical to the "
                f"serving artifact {aid}; nothing to promote"
            )
            return {"event": "promote_noop", **rec}
        ok, reason = eval_gate(
            metrics,
            incumbent["metrics"] if incumbent else None,
            metric=c.gate_metric,
            min_delta=c.gate_min_delta,
        )
        if self.tracer is not None:
            self.tracer.record(
                "eval-gate",
                t_start=t_gate_unix,
                dur_s=time.monotonic() - t_gate0,
                trace=trace,
                round=r,
                artifact=aid,
                passed=bool(ok),
            )
        rec: dict[str, Any] = {
            "round": r,
            "trigger": trigger,
            "artifact": aid,
            "gate": c.gate_metric,
            "reason": reason,
            "round_wall_s": round(round_wall, 3),
        }
        if self._slo_tightened:
            rec["slo_tightened"] = True
        if c.gate_metric in metrics:
            try:
                rec["metric_value"] = float(metrics[c.gate_metric])
            except (TypeError, ValueError):
                pass
        if not ok:
            # Regression: reject; the serving pointer stays on the
            # incumbent (the rollback IS the refusal to move it).
            self.stats.gate_rejections += 1
            self._m_gate_rejections.inc()
            self.registry.reject(aid, reason=reason)
            self._maybe_gc()
            rec["incumbent"] = incumbent["id"] if incumbent else None
            self._record("gate_rejected", **rec)
            log.info(
                f"[CONTROLLER] round {r}: candidate {aid} REJECTED "
                f"({reason}); serving pointer unchanged"
                + (f" ({rec['incumbent']})" if rec["incumbent"] else "")
            )
            return {"event": "gate_rejected", **rec}
        t_pro_unix = time.time()
        t_pro0 = time.monotonic()
        try:
            self.registry.promote(aid, to="shadow")
        except RegistryError as e:
            # Content-addressed dedup corner: a round whose aggregate is
            # bit-identical to the serving artifact has nothing to swap.
            rec["note"] = str(e)
            self._record("promote_noop", **rec)
            return {"event": "promote_noop", **rec}
        if self.shadow_gate is not None:
            # The candidate is now HELD in the shadow state: the fleet
            # manager mirrors live traffic onto it (shadow/), and the
            # pointer moves only on measured live agreement. Disagreement
            # — or no evidence inside the gate's patience — fails closed.
            ok_live, verdict = self.shadow_gate.wait(aid)
            rec["shadow_verdict"] = {
                k: verdict.get(k)
                for k in ("pairs", "flip_rate", "psi", "reason")
            }
            if not ok_live:
                self.stats.shadow_rejections += 1
                self._m_shadow_rejections.inc()
                self.registry.reject(
                    aid, reason=verdict["reason"], verdict=verdict
                )
                self._maybe_gc()
                rec["incumbent"] = incumbent["id"] if incumbent else None
                self._record("shadow_rejected", **rec)
                log.info(
                    f"[CONTROLLER] round {r}: candidate {aid} REJECTED by "
                    f"the live shadow gate ({verdict['reason']}); serving "
                    "pointer unchanged"
                    + (f" ({rec['incumbent']})" if rec["incumbent"] else "")
                )
                return {"event": "shadow_rejected", **rec}
        sup_candidate_err: float | None = None
        if self.label_gate is not None:
            # The supervised rung (labels/join.py): the candidate's
            # mirror pairs joined against delayed ground truth. A
            # candidate that flips nothing (clean flip-rate/PSI) but is
            # WRONG where the incumbent was right fails exactly here —
            # and "not enough joined labels" fails closed, never open.
            ok_sup, sup = self.label_gate.evaluate(aid)
            rec["label_verdict"] = {
                k: sup.get(k)
                for k in (
                    "joined", "coverage", "serving_error",
                    "candidate_error", "reason",
                )
            }
            if (
                self.error_monitor is not None
                and sup.get("serving_error") is not None
            ):
                # The same joined evidence doubles as the supervised
                # drift monitor's observation of the SERVING model.
                joined_n = int(sup.get("joined") or 0)
                self.error_monitor.observe(
                    int(round(float(sup["serving_error"]) * joined_n)),
                    joined_n,
                )
            if not ok_sup:
                self.stats.label_rejections += 1
                self._m_label_rejections.inc()
                self.registry.reject(aid, reason=sup["reason"], verdict=sup)
                self._maybe_gc()
                rec["incumbent"] = incumbent["id"] if incumbent else None
                self._record("label_rejected", **rec)
                log.info(
                    f"[CONTROLLER] round {r}: candidate {aid} REJECTED by "
                    f"the supervised label gate ({sup['reason']}); serving "
                    "pointer unchanged"
                    + (f" ({rec['incumbent']})" if rec["incumbent"] else "")
                )
                return {"event": "label_rejected", **rec}
            sup_candidate_err = sup.get("candidate_error")
        try:
            self.registry.promote(aid, to="serving")
        except RegistryError as e:
            rec["note"] = str(e)
            self._record("promote_noop", **rec)
            return {"event": "promote_noop", **rec}
        if self.tracer is not None:
            self.tracer.record(
                "promote",
                t_start=t_pro_unix,
                dur_s=time.monotonic() - t_pro0,
                trace=trace,
                round=r,
                artifact=aid,
            )
        latency = time.monotonic() - t_end
        self.stats.promotions += 1
        self._m_promotions.inc()
        self.stats.promotion_latency_s.append(latency)
        rec["promotion_latency_s"] = round(latency, 4)
        if self.drift is not None and eval_hist is not None:
            self.drift.set_reference(eval_hist)
        if self.error_monitor is not None and sup_candidate_err is not None:
            # The newly promoted model's supervised error anchors the
            # error-rate drift reference (the analogue of re-anchoring
            # the score-histogram reference above).
            self.error_monitor.set_reference(float(sup_candidate_err))
        self._maybe_gc()
        self._record("promoted", **rec)
        log.info(
            f"[CONTROLLER] round {r}: promoted {aid} to serving "
            f"({reason}; pointer swap {latency * 1e3:.0f} ms after round end)"
        )
        return {"event": "promoted", **rec}

    # ------------------------------------------------------------------- run
    def run(
        self,
        *,
        max_rounds: int | None = None,
        stop: threading.Event | None = None,
    ) -> ControllerStats:
        """The daemon loop: trigger-wait, cycle, repeat. ``max_rounds``
        bounds COMPLETED+failed cycles (None = until ``stop`` is set)."""
        stop = stop or threading.Event()
        cycles = 0
        while not stop.is_set():
            if max_rounds is not None and cycles >= max_rounds:
                break
            trigger = self._wait_for_trigger(stop)
            if trigger is None:
                break
            t0 = time.monotonic()
            self.run_cycle(trigger)
            self.stats.cycle_wall_s += time.monotonic() - t0
            cycles += 1
        log.info(
            f"[CONTROLLER] campaign halted: "
            f"{self.stats.rounds_completed} round(s) completed, "
            f"{self.stats.promotions} promoted, "
            f"{self.stats.gate_rejections} gate-rejected, "
            f"{self.stats.drift_triggers} drift-triggered"
        )
        return self.stats

    def summary(self) -> dict:
        s = asdict(self.stats)
        lat = s.pop("promotion_latency_s")
        s["promotion_latency_ms_mean"] = (
            round(float(np.mean(lat)) * 1e3, 3) if lat else None
        )
        return s
