"""Control plane: the unattended train -> gate -> promote -> serve ->
monitor loop.

The reference is one-shot: a federated round happens only when a human
re-runs three scripts, and nothing connects "a round finished" to "the
serving tier loads it". *Federated Learning in the Wild* (arxiv
2509.17836) shows cybersecurity FL degrading under non-IID drift unless
retraining is monitored and triggered, and *Exploring the Practicality
of Federated Learning* (arxiv 2405.20431) identifies the round
orchestration loop — not any single round — as the real efficiency
objective. This package is that loop:

* :mod:`.controller` — the long-lived ``fedtpu controller`` daemon:
  drives the existing TCP round engine (comm/server.py) round after
  round, evaluates every aggregate on a held-out split, registers it as
  an immutable candidate (registry/), and promotes it through the
  eval gate — a candidate worse than the incumbent is REJECTED and the
  serving pointer never moves (automatic rollback-by-refusal). A
  structured controller-state JSONL makes a restarted controller resume
  mid-campaign.
* :mod:`.drift` — score-distribution shift (PSI/KS) of live serving
  traffic (the serving tier's metrics-JSONL histogram export) against
  the promoted artifact's eval reference histogram; a fired verdict is
  what triggers the next training round instead of a fixed clock.
"""

from .controller import Controller, ControllerStats, SloActuator
from .drift import (
    DriftMonitor,
    ErrorRateMonitor,
    SentinelLink,
    cadence_interval_s,
    drift_cohort_fraction,
    ks_distance,
    psi,
)

__all__ = [
    "Controller",
    "ControllerStats",
    "DriftMonitor",
    "ErrorRateMonitor",
    "SentinelLink",
    "SloActuator",
    "cadence_interval_s",
    "drift_cohort_fraction",
    "ks_distance",
    "psi",
]
