"""Score-distribution drift: PSI/KS over serving-score histograms.

The detector's output distribution is the cheapest drift signal a
deployment already has: every scored flow produces one P(attack), the
serving tier bins them (serving/server.py exports a per-batch
``score_hist`` on the metrics-JSONL channel), and the promoted
artifact's manifest carries the histogram of the SAME model's scores on
the held-out eval split (train/fedeval.reference_histogram). When live
traffic stops looking like the validation traffic — new attack family,
topology change, seasonal shift — the two histograms diverge long before
anyone labels a flow.

Two standard distances over the binned distributions:

* **PSI** (population stability index): ``sum((o - e) * ln(o / e))``
  over bin fractions, the industry-standard monitoring score; > 0.25 is
  the classic "significant shift, retrain" bound.
* **KS**: max absolute CDF gap — bounded [0, 1], less sensitive to
  tail bins than PSI's log ratio.

:class:`DriftMonitor` tails the serving metrics-JSONL incrementally
(byte-offset resume, partial trailing lines left for the next poll) and
fires a verdict once enough scores accumulated AND the distance crosses
the threshold. Firing resets the observation window — one burst of
drifted traffic triggers one round, not one round per poll.
"""

from __future__ import annotations

import json
import os
from typing import Any

import numpy as np

from ..utils.logging import get_logger

log = get_logger()

_EPS = 1e-4  # empty-bin smoothing: PSI's log ratio must never see a zero


def _fractions(counts: Any) -> np.ndarray:
    c = np.asarray(counts, np.float64).ravel()
    if c.ndim != 1 or c.size < 2 or (c < 0).any():
        raise ValueError(f"histogram counts must be a 1-D >=2-bin non-negative array, got {c!r}")
    total = c.sum()
    if total <= 0:
        raise ValueError("histogram has no mass")
    return c / total


def _psi_terms(expected: Any, observed: Any) -> tuple:
    """(per-bin PSI terms, clipped e fractions, clipped o fractions) —
    the ONE copy of the clip/renormalize/term arithmetic both
    :func:`psi` (their sum) and :func:`psi_contributions` (their
    ranking) are defined over, so the localization decomposes the
    reported distance EXACTLY by construction."""
    e = np.clip(_fractions(expected), _EPS, None)
    o = np.clip(_fractions(observed), _EPS, None)
    if e.shape != o.shape:
        raise ValueError(f"bin count mismatch: {e.shape} vs {o.shape}")
    # Renormalize after clipping so both still sum to 1.
    e, o = e / e.sum(), o / o.sum()
    return (o - e) * np.log(o / e), e, o


def psi(expected: Any, observed: Any) -> float:
    """Population stability index between two count histograms (same
    binning). 0 = identical; > 0.25 = significant shift (classic bound)."""
    terms, _, _ = _psi_terms(expected, observed)
    return float(np.sum(terms))


def psi_contributions(
    expected: Any, observed: Any, *, top_k: int = 3
) -> list[dict]:
    """Per-bin PSI localization: WHICH score region moved.

    PSI is a sum of per-bin terms ``(o_i - e_i) * ln(o_i / e_i)`` (each
    >= 0 after the clipping both :func:`psi` and this function apply),
    so the bins sorted by term ARE the drift's location. A page that
    says "PSI 0.4" sends the operator histogram-diffing; one that says
    "bin 9 (the top score decile) holds 80% of the shift" says a new
    attack family is scoring hot — the ROADMAP's drift-localization
    residual. Ties break toward the lower bin index (deterministic
    output for identical inputs).

    Returns the ``top_k`` bins as ``{"bin": i, "psi": term,
    "expected_frac": e_i, "observed_frac": o_i}``, largest term first,
    zero-contribution bins omitted. Built on the SAME ``_psi_terms``
    arithmetic as :func:`psi`, so ``sum(term over ALL bins) == psi()``
    exactly by construction.
    """
    terms, e, o = _psi_terms(expected, observed)
    order = sorted(
        range(terms.size), key=lambda i: (-terms[i], i)
    )[: max(int(top_k), 0)]
    return [
        {
            "bin": int(i),
            "psi": round(float(terms[i]), 6),
            "expected_frac": round(float(e[i]), 6),
            "observed_frac": round(float(o[i]), 6),
        }
        for i in order
        if terms[i] > 0.0
    ]


def cadence_interval_s(
    drift: float,
    *,
    threshold: float,
    min_s: float,
    max_s: float | None,
    urgency_span: float = 2.0,
) -> float:
    """Adaptive round cadence: map a fired verdict's drift MAGNITUDE to
    the controller's next inter-round interval.

    A verdict always means ``drift >= threshold``, but 0.26 and 2.6 are
    different emergencies: the first is a slow seasonal shift the fleet
    can absorb on a relaxed cadence, the second is a new attack family
    scoring hot right now. Linear interpolation between the configured
    bounds: at the bare threshold the interval stays at ``max_s`` (the
    relaxed clock), at ``urgency_span * threshold`` or beyond it floors
    at ``min_s`` (back-to-back throttle only). Pure arithmetic — no
    clock reads, unit-testable from synthetic verdicts — and with
    ``max_s`` unset (purely drift-driven campaigns with no clock at
    all) it degrades to ``min_s``.
    """
    min_s = float(min_s)
    if max_s is None or float(max_s) <= min_s:
        return min_s
    threshold = float(threshold)
    hi = threshold * float(urgency_span)
    if hi <= threshold:
        return min_s
    frac = (float(drift) - threshold) / (hi - threshold)
    frac = min(max(frac, 0.0), 1.0)
    return float(max_s) - (float(max_s) - min_s) * frac


def drift_cohort_fraction(
    drift: float,
    *,
    threshold: float,
    min_frac: float,
    max_frac: float,
    urgency_span: float = 2.0,
) -> float:
    """Drift-scaled client sampling: map a fired verdict's drift
    MAGNITUDE to the fraction of the fleet the next round must hear
    from (ISSUE 18 — cadence already adapts via
    :func:`cadence_interval_s`; cohort SIZE now does too).

    The inverse shape of the cadence map: at the bare threshold the
    round keeps the small steady-state quorum (``min_frac`` of the
    fleet — a routine refresh), at ``urgency_span * threshold`` or
    beyond it demands ``max_frac`` (a new attack family needs the
    widest, most representative update the fleet can produce — exactly
    when label-skewed non-IID cohorts mislead the most). Pure
    arithmetic, same interpolation discipline as the cadence map, so
    one unit test pins both ends and the midpoint.
    """
    min_frac = min(max(float(min_frac), 0.0), 1.0)
    max_frac = min(max(float(max_frac), 0.0), 1.0)
    if max_frac <= min_frac:
        return min_frac
    threshold = float(threshold)
    hi = threshold * float(urgency_span)
    if hi <= threshold:
        return max_frac
    frac = (float(drift) - threshold) / (hi - threshold)
    frac = min(max(frac, 0.0), 1.0)
    return min_frac + (max_frac - min_frac) * frac


def ks_distance(expected: Any, observed: Any) -> float:
    """Max absolute CDF gap between two count histograms (same binning)."""
    e = _fractions(expected)
    o = _fractions(observed)
    if e.shape != o.shape:
        raise ValueError(f"bin count mismatch: {e.shape} vs {o.shape}")
    return float(np.max(np.abs(np.cumsum(o) - np.cumsum(e))))


class DriftMonitor:
    """Accumulate live serving-score histograms; fire on distribution
    shift vs the promoted artifact's eval reference.

    Sources compose: :meth:`observe` ingests a histogram directly (tests,
    in-process wiring) and :meth:`poll` tails a serving metrics-JSONL
    file for ``serve_batch`` records carrying ``score_hist`` (the
    cross-process wiring — ``fedtpu infer-serve --metrics-jsonl X`` plus
    ``fedtpu controller --drift-jsonl X``). Either way :meth:`check`
    decides; a fired verdict resets the window.

    The reference histogram is per-PROMOTION state: the controller calls
    :meth:`set_reference` with each newly promoted artifact's eval
    histogram, which also resets the window (scores produced by the old
    model must not count against the new reference).
    """

    def __init__(
        self,
        jsonl_path: str | None = None,
        *,
        reference: Any | None = None,
        threshold: float = 0.25,
        min_scores: int = 256,
        method: str = "psi",
        window_scores: int | None = None,
    ):
        if method not in ("psi", "ks"):
            raise ValueError(f"method={method!r} must be 'psi' or 'ks'")
        if threshold <= 0.0:
            raise ValueError(f"threshold={threshold} must be > 0")
        self.jsonl_path = jsonl_path
        self.threshold = float(threshold)
        self.min_scores = int(min_scores)
        self.method = method
        # Observation-window cap (exponential decay): once the window
        # holds this many scores, each new ingestion halves the existing
        # counts — an UNBOUNDED window would let a week of stable traffic
        # dilute a fresh shift so far below threshold that the trigger
        # fires days late (recent traffic must stay a constant fraction
        # of the window). Default: 64x the verdict floor.
        self.window_scores = (
            64 * self.min_scores if window_scores is None else int(window_scores)
        )
        if self.window_scores < self.min_scores:
            raise ValueError(
                f"window_scores={self.window_scores} below "
                f"min_scores={self.min_scores}"
            )
        self._ref: np.ndarray | None = None
        self._obs: np.ndarray | None = None
        self._offset = 0  # resume point into the JSONL tail
        if reference is not None:
            self.set_reference(reference)

    # ------------------------------------------------------------ ingestion
    def set_reference(self, counts: Any) -> None:
        """Adopt a newly promoted artifact's eval histogram; resets the
        observation window (old-model scores must not fire against it)
        AND fast-forwards the JSONL tail to end-of-file — records already
        on disk were scored by the OLD model (during the training round,
        or a whole backlog on controller restart) and counting them
        against the new reference would fire a spurious round right after
        every promotion."""
        self._ref = np.asarray(counts, np.int64).ravel()
        _fractions(self._ref)  # validate now, not at check time
        self.reset_window()
        if self.jsonl_path is not None:
            try:
                self._offset = os.path.getsize(self.jsonl_path)
            except OSError:
                self._offset = 0

    def reset_window(self) -> None:
        self._obs = None

    @property
    def has_reference(self) -> bool:
        return self._ref is not None

    @property
    def observed_scores(self) -> int:
        return 0 if self._obs is None else int(self._obs.sum())

    def observe(self, counts: Any) -> None:
        c = np.asarray(counts, np.int64).ravel()
        if (c < 0).any():
            # Validate on INGESTION, not at check(): a malformed record in
            # the tailed JSONL must be skipped by _ingest_jsonl's guard,
            # never poison the window and crash the controller daemon at
            # verdict time.
            raise ValueError(f"negative histogram counts {c.tolist()}")
        if self._ref is not None and c.shape != self._ref.shape:
            raise ValueError(
                f"observed histogram has {c.size} bins, reference has "
                f"{self._ref.size} — serving and eval must bin identically"
            )
        if self._obs is None:
            self._obs = c
        else:
            if self._obs.sum() >= self.window_scores:
                self._obs //= 2  # decay old traffic; recency must matter
            self._obs = self._obs + c

    def poll(self) -> dict | None:
        """Tail the JSONL for new ``serve_batch`` score histograms, then
        :meth:`check`. Returns the fired verdict dict or None."""
        if self.jsonl_path is not None:
            self._ingest_jsonl()
        return self.check()

    def _ingest_jsonl(self) -> None:
        # Shared incremental tail (obs/timeline.py): complete lines
        # only, truncation restarts at 0, missing file is empty.
        from ..obs.timeline import read_new_jsonl_lines

        self._offset, lines = read_new_jsonl_lines(
            self.jsonl_path, self._offset
        )
        for line in lines:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("phase") != "serve_batch":
                continue
            hist = rec.get("score_hist")
            if isinstance(hist, list) and hist:
                try:
                    self.observe(hist)
                except ValueError as e:
                    log.warning(f"[DRIFT] skipping malformed score_hist: {e}")

    # -------------------------------------------------------------- verdict
    def distance(self) -> tuple[float | None, int]:
        """(current distance or None when undecidable, scores observed)."""
        n = self.observed_scores
        if self._ref is None or self._obs is None or n == 0:
            return None, n
        fn = psi if self.method == "psi" else ks_distance
        return fn(self._ref, self._obs), n

    def check(self) -> dict | None:
        """Fire when >= min_scores accumulated and distance >= threshold.
        A fired verdict resets the window."""
        d, n = self.distance()
        if d is None or n < self.min_scores:
            return None
        if d < self.threshold:
            return None
        verdict = {
            "drift": round(d, 6),
            "method": self.method,
            "threshold": self.threshold,
            "scores": n,
            # Localization: the top per-bin PSI contributions (computed
            # regardless of the verdict method — PSI's additive terms
            # are the localization; KS's max-gap is not decomposable).
            "top_bins": psi_contributions(self._ref, self._obs),
        }
        top = verdict["top_bins"]
        where = (
            ", ".join(
                f"bin {b['bin']} ({b['psi']:.3f})" for b in top
            )
            if top
            else "no single bin dominates"
        )
        log.info(
            f"[DRIFT] {self.method}={d:.4f} >= {self.threshold} over {n} "
            f"live scores — triggering a training round (moved: {where})"
        )
        self.reset_window()
        return verdict


class ErrorRateMonitor:
    """Supervised drift: the serving model's measured error over joined
    ground truth (labels/join.py) vs its reference error.

    PSI/KS fire when the traffic stops LOOKING like the validation
    split; they are blind to traffic that looks the same but is now
    labeled differently (an attack family the model scores cold —
    volatile encrypted-flow distributions make the score-only trigger
    noisy in both directions). This monitor consumes the delayed
    ground-truth plane instead: ingest joined ``(wrong, total)`` counts
    — e.g. a join report's serving-side verdict — and fire once enough
    joined flows accumulated AND the error rate exceeds the reference
    by ``margin``. Same lifecycle as :class:`DriftMonitor`: a fired
    verdict resets the window, and the controller re-references on each
    promotion (the new model's error anchors the next comparison).
    """

    def __init__(
        self,
        *,
        reference_error: float | None = None,
        margin: float = 0.05,
        min_joined: int = 64,
    ):
        if float(margin) <= 0.0:
            raise ValueError(f"margin={margin} must be > 0")
        if int(min_joined) < 1:
            raise ValueError(f"min_joined={min_joined} must be >= 1")
        self.margin = float(margin)
        self.min_joined = int(min_joined)
        self._ref: float | None = None
        self._wrong = 0
        self._total = 0
        if reference_error is not None:
            self.set_reference(reference_error)

    # ------------------------------------------------------------ ingestion
    def set_reference(self, error: float) -> None:
        if not 0.0 <= float(error) <= 1.0:
            raise ValueError(f"reference error {error} must be in [0, 1]")
        self._ref = float(error)
        self.reset_window()

    def reset_window(self) -> None:
        self._wrong = 0
        self._total = 0

    @property
    def has_reference(self) -> bool:
        return self._ref is not None

    @property
    def observed_joined(self) -> int:
        return self._total

    def observe(self, wrong: int, total: int) -> None:
        if int(wrong) < 0 or int(total) < int(wrong):
            raise ValueError(
                f"need 0 <= wrong <= total, got wrong={wrong} total={total}"
            )
        self._wrong += int(wrong)
        self._total += int(total)

    def observe_verdict(self, verdict: Any) -> None:
        """Ingest one supervised verdict dict (labels/join.py
        ``supervised_verdict`` shape: ``n`` joined flows, ``error``)."""
        n = int(verdict.get("n", 0) or 0)
        err = verdict.get("error")
        if n > 0 and err is not None:
            self.observe(round(float(err) * n), n)

    # -------------------------------------------------------------- verdict
    def check(self) -> dict | None:
        """Fire when >= min_joined flows joined and the measured error
        exceeds reference + margin. A fired verdict resets the window."""
        if self._ref is None or self._total < self.min_joined:
            return None
        err = self._wrong / self._total
        if err < self._ref + self.margin:
            return None
        verdict = {
            "drift": round(err - self._ref, 6),
            "method": "error_rate",
            "threshold": self.margin,
            "scores": self._total,
            "error": round(err, 6),
            "reference_error": round(self._ref, 6),
        }
        log.info(
            f"[DRIFT] supervised error {err:.4f} >= reference "
            f"{self._ref:.4f} + {self.margin} over {self._total} joined "
            "flow(s) — triggering a training round"
        )
        self.reset_window()
        return verdict


class SentinelLink:
    """The controller's tail of the sentinel's verdicts-JSONL — the
    cross-process poke that turns a between-gates supervised-drift
    verdict (obs/sentinel.py JournalTail) into a corrective round.

    Same incremental discipline as :class:`DriftMonitor`'s metrics tail:
    byte-offset resume, complete lines only, foreign lines skipped. The
    offset initializes to the file's CURRENT end — a restarted
    controller must not replay last week's verdicts as fresh triggers.
    ``poll()`` returns the newest verdict since the last poll (one
    trigger per poll even if several fired while training ran — the
    corrective round answers all of them) or None."""

    #: The verdict schema the sentinel journals (obs/sentinel.py).
    SCHEMA = "fedtpu-sentinel-verdict-v1"

    def __init__(self, path: str):
        self.path = path
        self._offset = 0
        try:
            self._offset = os.path.getsize(path)
        except OSError:
            pass  # not written yet — start from 0 when it appears
        self.seen = 0

    def poll(self) -> dict | None:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return None
        if size < self._offset:
            self._offset = 0  # rotated/truncated underneath us
        if size == self._offset:
            return None
        with open(self.path, "rb") as f:
            f.seek(self._offset)
            chunk = f.read(size - self._offset)
        # Only complete lines; a torn tail waits for the next poll.
        end = chunk.rfind(b"\n")
        if end < 0:
            return None
        self._offset += end + 1
        latest: dict | None = None
        for raw in chunk[: end + 1].splitlines():
            try:
                rec = json.loads(raw)
            except json.JSONDecodeError:
                continue
            if not isinstance(rec, dict) or rec.get("schema") != self.SCHEMA:
                continue
            if "drift" not in rec or "method" not in rec:
                continue
            self.seen += 1
            latest = rec
        return latest
