"""Wire-domain pass: the comm/wire.py protocol-constant invariants.

The frame vocabulary is a hand-maintained namespace: every ``*_MAGIC``
discriminates a frame type on a shared TCP stream, every ``*_DOMAIN``
(and the per-direction domains inside ``_STREAM_DOMAINS``) separates an
HMAC universe. Two constants silently sharing bytes is the PR-7
reflection-hole class — a client's own authenticated upload chunks
verified as "aggregate" bytes because up and down shared a domain. The
three rules here make that class a lint error:

``wire-domain-unique``
    All magic/domain byte values globally unique; magics exactly 4
    bytes (the framing layer sniffs a fixed-width discriminator);
    domain strings versioned (``...-v<N>`` suffix) so a semantic change
    can be expressed as a new disjoint domain instead of a silent
    reinterpretation of the old one.

``wire-magic-coverage``
    Every magic is consumed on both sides: referenced from at least two
    function scopes (its encode and its decode), and reachable from
    outside comm/wire.py — either the name itself is referenced by a
    dispatch module, or a wire.py function whose body uses it is.
    A magic nobody dispatches is a dead frame type; a frame type whose
    4-byte literal lives outside wire.py is an untracked one (also
    flagged: uppercase 4-byte bytes literals outside wire.py).

``wire-stream-direction``
    Every call to the stream frame codecs (``encode_stream_header``,
    ``decode_stream_chunk``, ...) outside wire.py must pass an explicit
    ``direction=`` keyword. The parameter defaults to ``"up"`` for the
    upload tier's history; a reply-side call site that forgets it gets
    upload-domain tags — exactly the reflection hole — and this rule
    makes the omission visible statically.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from .core import (
    Finding,
    Project,
    bytes_const,
    call_name,
    kwarg,
    register,
    str_const,
)

WIRE_REL = "comm/wire.py"
#: The wire layer: the modules allowed to DEFINE frame magics / HMAC
#: domains. comm/framing.py owns the transport envelope (FRAME_MAGIC,
#: ACK), comm/secure.py the secure-agg sub-protocol frames; everything
#: else must import, so uniqueness stays checkable in one pass.
WIRE_LAYER_RELS = ("comm/wire.py", "comm/framing.py", "comm/secure.py")
_DOMAIN_VERSION_RE = re.compile(rb"-v\d+$")
_MAGIC_LITERAL_RE = re.compile(rb"^[A-Z]{4}$")

#: Stream codecs whose ``direction`` kwarg selects the HMAC domain set.
DIRECTIONAL_FNS = frozenset(
    {
        "encode_stream_header",
        "decode_stream_header",
        "encode_stream_chunk",
        "decode_stream_chunk",
        "encode_stream_end",
        "decode_stream_end",
    }
)


def _wire_constants(
    project: Project,
) -> tuple[dict[str, tuple], dict[str, tuple]]:
    """(magics, domains): name -> (value, line, module). Collected
    across the wire-layer modules. Magics are ``*_MAGIC`` assignments
    plus any magic-shaped (4-byte uppercase) module-level bytes
    constant (framing's ``ACK``); domains are ``*_DOMAIN`` assignments
    plus the bytes literals inside wire.py's ``_STREAM_DOMAINS``
    direction table (keyed ``_STREAM_DOMAINS[dir][i]`` so a duplicate
    is nameable in a finding)."""
    magics: dict[str, tuple] = {}
    domains: dict[str, tuple] = {}
    for rel in WIRE_LAYER_RELS:
        mod = project.module(rel)
        if mod is None or mod.tree is None:
            continue
        for node in mod.tree.body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            if not isinstance(target, ast.Name):
                continue
            name = target.id
            value = bytes_const(node.value)
            if value is not None and (
                name.endswith("_MAGIC") or _MAGIC_LITERAL_RE.match(value)
            ):
                magics[name] = (value, node.lineno, mod)
            elif value is not None and name.endswith("_DOMAIN"):
                domains[name] = (value, node.lineno, mod)
            elif name == "_STREAM_DOMAINS" and isinstance(node.value, ast.Dict):
                for key_node, val_node in zip(
                    node.value.keys, node.value.values
                ):
                    direction = (
                        key_node.value
                        if isinstance(key_node, ast.Constant)
                        else "?"
                    )
                    elts = (
                        val_node.elts
                        if isinstance(val_node, (ast.Tuple, ast.List))
                        else []
                    )
                    for i, elt in enumerate(elts):
                        v = bytes_const(elt)
                        # Name-valued entries alias *_DOMAIN constants
                        # picked up above; only literals add values here.
                        if v is not None:
                            domains[
                                f"_STREAM_DOMAINS[{direction!r}][{i}]"
                            ] = (v, elt.lineno, mod)
    return magics, domains


@register(
    "wire-domain-unique",
    "comm/wire.py magic/domain byte values globally unique, magics 4 "
    "bytes, HMAC domains versioned",
)
def check_domain_unique(project: Project) -> Iterator[Finding]:
    wire = project.module(WIRE_REL)
    if wire is None:
        return
    magics, domains = _wire_constants(project)
    if not magics or not domains:
        yield Finding(
            "wire-domain-unique",
            wire.rel,
            1,
            "no *_MAGIC/*_DOMAIN constants found in the wire layer — the "
            "wire-domain pass has lost its anchor (renamed constants?)",
        )
        return
    by_value: dict[bytes, str] = {}
    for name, (value, line, mod) in {**magics, **domains}.items():
        prior = by_value.get(value)
        if prior is not None:
            yield Finding(
                "wire-domain-unique",
                mod.rel,
                line,
                f"{name} duplicates the byte value of {prior} "
                f"({value!r}) — frame/HMAC universes must be disjoint",
            )
        else:
            by_value[value] = name
    for name, (value, line, mod) in magics.items():
        if len(value) != 4:
            yield Finding(
                "wire-domain-unique",
                mod.rel,
                line,
                f"{name} is {len(value)} bytes ({value!r}); frame magics "
                "are a fixed 4-byte discriminator",
            )
    for name, (value, line, mod) in domains.items():
        if not _DOMAIN_VERSION_RE.search(value):
            yield Finding(
                "wire-domain-unique",
                mod.rel,
                line,
                f"{name} ({value!r}) lacks a '-v<N>' version suffix — "
                "domain semantics changes must mint a NEW disjoint "
                "domain, not reinterpret the old bytes",
            )


def _function_scopes(module) -> list[tuple[str, ast.AST]]:
    """Top-level + nested function defs of a module (name, node)."""
    out = []
    for node in module.walk():
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append((node.name, node))
    return out


def _names_in(node: ast.AST) -> set[str]:
    return {
        n.id for n in ast.walk(node) if isinstance(n, ast.Name)
    } | {
        n.attr for n in ast.walk(node) if isinstance(n, ast.Attribute)
    }


@register(
    "wire-magic-coverage",
    "every frame magic has encode+decode scopes and an out-of-module "
    "consumer; no ad-hoc 4-byte magic literals outside comm/wire.py",
)
def check_magic_coverage(project: Project) -> Iterator[Finding]:
    magics, _ = _wire_constants(project)
    if not magics:
        return
    # Per-module: every identifier referenced, and per-function-scope
    # identifier sets — one AST walk each, shared by all magics.
    all_idents: dict[str, set[str]] = {}
    fn_scope_names: dict[str, dict[str, set[str]]] = {}
    for m in project.modules:
        all_idents[m.rel] = _names_in(m.tree) if m.tree is not None else set()
        fn_scope_names[m.rel] = {
            name: _names_in(node) for name, node in _function_scopes(m)
        }

    for name, (_value, line, mod) in magics.items():
        # Encode+decode coverage: the magic must be consumed from at
        # least two distinct function scopes anywhere in the package
        # (its build side and its parse/dispatch side).
        scopes = {
            (rel, fn)
            for rel, fns in fn_scope_names.items()
            for fn, names in fns.items()
            if name in names
        }
        if len(scopes) < 2:
            yield Finding(
                "wire-magic-coverage",
                mod.rel,
                line,
                f"{name} is referenced from {len(scopes)} function "
                "scope(s) package-wide — a frame type needs both an "
                "encode and a decode/dispatch side",
            )
            continue
        # Dispatch coverage: the constant (or a defining-module function
        # that uses it) must be consumed outside its defining module.
        refs_outside = any(
            name in idents
            for rel, idents in all_idents.items()
            if rel != mod.rel
        )
        using_fns = {fn for rel, fn in scopes if rel == mod.rel}
        fn_used_outside = any(
            fn in idents
            for rel, idents in all_idents.items()
            if rel != mod.rel
            for fn in using_fns
        )
        if not refs_outside and not fn_used_outside:
            yield Finding(
                "wire-magic-coverage",
                mod.rel,
                line,
                f"{name} is never dispatched: neither the constant nor "
                f"any {mod.rel} function using it is referenced from "
                "another module (dead frame type?)",
            )

    wire_layer = {m.rel for m in project.select(WIRE_LAYER_RELS)}
    for m in project.modules:
        if m.rel in wire_layer:
            continue
        for node in m.walk():
            v = bytes_const(node)
            if v is not None and _MAGIC_LITERAL_RE.match(v):
                yield Finding(
                    "wire-magic-coverage",
                    m.rel,
                    node.lineno,
                    f"4-byte magic-shaped bytes literal {v!r} outside the "
                    "wire layer (comm/wire.py, comm/framing.py, "
                    "comm/secure.py) — frame magics live there so "
                    "uniqueness stays checkable",
                )


@register(
    "wire-stream-direction",
    "stream frame codec calls outside comm/wire.py must pass an "
    "explicit direction= (disjoint up/down HMAC domains)",
)
def check_stream_direction(project: Project) -> Iterator[Finding]:
    for m in project.modules:
        if m.rel.endswith(WIRE_REL):
            continue
        for node in m.walk():
            if not isinstance(node, ast.Call):
                continue
            target = call_name(node)
            fn = target.rsplit(".", 1)[-1]
            if fn not in DIRECTIONAL_FNS:
                continue
            if kwarg(node, "direction") is None:
                yield Finding(
                    "wire-stream-direction",
                    m.rel,
                    node.lineno,
                    f"{fn}() called without an explicit direction= — the "
                    "default ('up') selects upload-tier HMAC domains; a "
                    "reply-side caller inheriting it reopens the "
                    "reflection hole",
                )


#: Modules allowed to DECLARE wire meta keys — the plain-JSON capability
#: adverts and markers riding upload/reply meta (stream chunk advert,
#: streamed-reply advert, re-home marker, subtree contributor record).
#: obs/trace.py owns the trace-identity key. Everywhere else must
#: import, so key-string uniqueness stays checkable in one pass exactly
#: like the magic/domain byte universes.
META_KEY_RELS = ("comm/wire.py", "obs/trace.py")


@register(
    "wire-meta-key-unique",
    "*_META_KEY meta-field names declared only in the wire layer "
    "(comm/wire.py, obs/trace.py), non-empty string literals, globally "
    "unique",
)
def check_meta_key_unique(project: Project) -> Iterator[Finding]:
    wire = project.module(WIRE_REL)
    if wire is None:
        return
    seen: dict[str, str] = {}
    declared = 0
    for m in project.modules:
        in_layer = any(m.rel.endswith(rel) for rel in META_KEY_RELS)
        if m.tree is None:
            continue
        for node in m.tree.body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            if not isinstance(target, ast.Name) or not target.id.endswith(
                "_META_KEY"
            ):
                continue
            if not in_layer:
                yield Finding(
                    "wire-meta-key-unique",
                    m.rel,
                    node.lineno,
                    f"{target.id} declared outside the wire layer "
                    f"({' | '.join(META_KEY_RELS)}) — meta keys must "
                    "live where their uniqueness is checkable in one "
                    "pass (import the constant instead)",
                )
                continue
            declared += 1
            value = str_const(node.value)
            if not value:
                yield Finding(
                    "wire-meta-key-unique",
                    m.rel,
                    node.lineno,
                    f"{target.id} must be a non-empty string literal "
                    "(meta keys are plain-JSON field names)",
                )
                continue
            prior = seen.get(value)
            if prior is not None:
                yield Finding(
                    "wire-meta-key-unique",
                    m.rel,
                    node.lineno,
                    f"{target.id} duplicates the meta-key string of "
                    f"{prior} ({value!r}) — two capabilities sharing one "
                    "meta field would silently shadow each other on old "
                    "peers",
                )
            else:
                seen[value] = target.id
    if declared == 0:
        yield Finding(
            "wire-meta-key-unique",
            wire.rel,
            1,
            "no *_META_KEY constants found in the wire layer — the "
            "meta-key pass has lost its anchor (renamed constants?)",
        )
