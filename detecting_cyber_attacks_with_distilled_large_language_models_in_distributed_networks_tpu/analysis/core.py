"""The pass framework behind ``fedtpu check``.

Design constraints, in order:

1. **Pure AST** — the checker never imports the code it scans, so a
   seeded-mutation self-test can point it at a temp copy of the tree
   (tests/test_analysis.py) and a broken module can't crash the linter
   that is supposed to flag it.
2. **Reviewed suppressions only** — a finding disappears exactly two
   ways: a per-line ``# fedtpu: allow(<rule>): reason`` pragma at the
   finding site (the reviewed-in-place form), or an entry in the
   repo-root ``ANALYSIS_BASELINE.json`` (the reviewed-at-a-distance
   form, for findings whose site is a poor home for a comment). Both
   carry a human reason; neither is emitted by tooling.
3. **Stable identity** — findings are keyed (rule, path, message), NOT
   line numbers, so a baseline survives unrelated edits above the
   finding; messages therefore name symbols, not offsets.

Exit-code contract (cli/check.py): 0 = clean (baselined/pragma'd
findings allowed), 1 = at least one non-baselined finding, 2 = usage
or internal error. bench.py's ``check`` record asserts
``check_findings_new == 0`` and exits 3 when the tree regresses.
"""

from __future__ import annotations

import ast
import json
import os
import re
import time
from dataclasses import dataclass, field
from typing import Callable, Iterable, Iterator, Mapping

#: Per-line suppression: ``# fedtpu: allow(rule)`` or
#: ``# fedtpu: allow(rule-a, rule-b): one-line reason``. The pragma
#: suppresses matching rules on ITS line and, when the pragma line is a
#: comment-only line, on the next code line (multi-line statements keep
#: the reason adjacent instead of trailing a 100-char expression).
PRAGMA_RE = re.compile(r"#\s*fedtpu:\s*allow\(([A-Za-z0-9_\-, ]+)\)")

#: Default baseline filename, resolved against the scanned root.
BASELINE_NAME = "ANALYSIS_BASELINE.json"


@dataclass(frozen=True)
class Finding:
    """One rule violation at one site."""

    rule: str
    path: str  # root-relative, forward slashes
    line: int
    message: str

    @property
    def key(self) -> tuple[str, str, str]:
        """Baseline identity — line numbers excluded on purpose (they
        churn under unrelated edits; messages name symbols instead)."""
        return (self.rule, self.path, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }


class SourceModule:
    """One parsed source file: AST + lines + pragma map."""

    def __init__(self, root: str, path: str):
        self.abspath = path
        self.rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, "r", encoding="utf-8") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        # A file the interpreter can't parse is reported as a finding by
        # the project scan itself (rule "parse"), with tree=None; rules
        # must tolerate missing trees.
        try:
            self.tree: ast.Module | None = ast.parse(
                self.source, filename=self.rel
            )
        except SyntaxError as e:
            self.tree = None
            self.syntax_error = f"{e.msg} (line {e.lineno})"
        else:
            self.syntax_error = None
        self._allow = self._parse_pragmas()

    def _parse_pragmas(self) -> dict[int, frozenset[str]]:
        allow: dict[int, set[str]] = {}
        for i, text in enumerate(self.lines, start=1):
            m = PRAGMA_RE.search(text)
            if not m:
                continue
            rules = frozenset(
                r.strip() for r in m.group(1).split(",") if r.strip()
            )
            allow.setdefault(i, set()).update(rules)
            # A comment-only pragma covers the comment block it starts
            # plus the first code line after it (the reason may wrap).
            if text.lstrip().startswith("#"):
                j = i + 1
                while j <= len(self.lines) and self.lines[
                    j - 1
                ].lstrip().startswith("#"):
                    allow.setdefault(j, set()).update(rules)
                    j += 1
                allow.setdefault(j, set()).update(rules)
        return {k: frozenset(v) for k, v in allow.items()}

    def allowed(self, rule: str, line: int) -> bool:
        rules = self._allow.get(line)
        return bool(rules) and (rule in rules or "all" in rules)

    def walk(self) -> Iterator[ast.AST]:
        if self.tree is None:
            return iter(())
        return ast.walk(self.tree)


class Project:
    """The scanned tree: every package module + top-level scripts.

    ``root`` is the repo root; packages are its top-level directories
    carrying an ``__init__.py`` (``tests/`` is excluded — test files
    intentionally embed violating snippets as fixtures), plus the
    top-level ``*.py`` entry points (bench.py, __graft_entry__.py).
    """

    EXCLUDE_DIRS = {"tests", "__pycache__", ".git", ".claude"}

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.modules: list[SourceModule] = []
        for path in sorted(self._source_paths()):
            self.modules.append(SourceModule(self.root, path))
        self._by_rel = {m.rel: m for m in self.modules}

    def _source_paths(self) -> Iterator[str]:
        for entry in sorted(os.listdir(self.root)):
            full = os.path.join(self.root, entry)
            if entry.endswith(".py") and os.path.isfile(full):
                yield full
            elif (
                os.path.isdir(full)
                and entry not in self.EXCLUDE_DIRS
                and os.path.isfile(os.path.join(full, "__init__.py"))
            ):
                for dirpath, dirnames, filenames in os.walk(full):
                    dirnames[:] = [
                        d for d in dirnames if d not in self.EXCLUDE_DIRS
                    ]
                    for fn in filenames:
                        if fn.endswith(".py"):
                            yield os.path.join(dirpath, fn)

    def module(self, rel_suffix: str) -> SourceModule | None:
        """Look a module up by root-relative path suffix (the package
        directory name varies between the repo and a test's temp copy,
        so rules address ``comm/wire.py``, not the full path)."""
        for m in self.modules:
            if m.rel == rel_suffix or m.rel.endswith("/" + rel_suffix):
                return m
        return None

    def select(self, rel_suffixes: Iterable[str]) -> list[SourceModule]:
        out = []
        for suf in rel_suffixes:
            if suf.endswith("/"):
                out.extend(
                    m
                    for m in self.modules
                    if ("/" + suf) in ("/" + m.rel)
                    or m.rel.startswith(suf)
                )
            else:
                m = self.module(suf)
                if m is not None:
                    out.append(m)
        return out


@dataclass
class Rule:
    """A named pass: ``fn(project) -> iterable of Finding``."""

    name: str
    description: str
    fn: Callable[[Project], Iterable[Finding]]

    def run(self, project: Project) -> list[Finding]:
        return list(self.fn(project))


_REGISTRY: dict[str, Rule] = {}


def register(name: str, description: str):
    """Decorator: add a pass to the default rule set."""

    def deco(fn: Callable[[Project], Iterable[Finding]]):
        _REGISTRY[name] = Rule(name, description, fn)
        return fn

    return deco


def all_rules() -> dict[str, Rule]:
    """Name -> Rule for the full default set (imports the rule modules
    lazily so ``analysis.core`` stays importable on its own)."""
    from . import (  # noqa: F401
        determinism_rules,
        obs_rules,
        thread_rules,
        wire_rules,
    )

    return dict(_REGISTRY)


# ------------------------------------------------------------------ baseline
def load_baseline(path: str) -> dict[tuple[str, str, str], str]:
    """Baseline file -> {finding key: reason}. Every entry must carry a
    non-empty ``reason`` — the baseline is a reviewed artifact, not a
    dumping ground (an empty reason raises)."""
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    out: dict[tuple[str, str, str], str] = {}
    for entry in data.get("findings", ()):
        reason = str(entry.get("reason", "")).strip()
        if not reason:
            raise ValueError(
                f"baseline entry for {entry.get('rule')}:{entry.get('path')} "
                "has no reason — baselines are reviewed suppressions"
            )
        out[(str(entry["rule"]), str(entry["path"]), str(entry["message"]))] = (
            reason
        )
    return out


def prune_baseline(path: str, stale: Iterable[Mapping]) -> int:
    """Rewrite the baseline at ``path`` minus the given stale entries
    (the remediation path for ``fedtpu check``'s reported-not-failed
    stale findings: ``--prune-baseline``). Every other field — the
    review comment, entry order, the reasons of entries that still fire
    — survives byte-for-byte in spirit (same JSON shape, 2-space
    indent). Atomic replace, so a crashed prune never leaves a torn
    baseline. Returns the number of entries removed."""
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    stale_keys = {
        (str(e["rule"]), str(e["path"]), str(e["message"])) for e in stale
    }
    findings = list(data.get("findings", ()))
    kept = [
        e
        for e in findings
        if (str(e.get("rule")), str(e.get("path")), str(e.get("message")))
        not in stale_keys
    ]
    removed = len(findings) - len(kept)
    if removed == 0:
        return 0
    data["findings"] = kept
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2)
        f.write("\n")
    os.replace(tmp, path)
    return removed


@dataclass
class CheckResult:
    """One ``fedtpu check`` run's outcome."""

    new: list[Finding] = field(default_factory=list)
    baselined: list[Finding] = field(default_factory=list)
    allowed: int = 0  # pragma-suppressed count
    stale_baseline: list[dict] = field(default_factory=list)
    runtime_s: float = 0.0
    rules_run: tuple[str, ...] = ()
    modules_scanned: int = 0

    @property
    def exit_code(self) -> int:
        return 1 if self.new else 0

    def to_dict(self) -> dict:
        return {
            "findings_new": [f.to_dict() for f in self.new],
            "findings_baselined": len(self.baselined),
            "findings_allowed": self.allowed,
            "stale_baseline": self.stale_baseline,
            "check_runtime_s": self.runtime_s,
            "rules": list(self.rules_run),
            "modules_scanned": self.modules_scanned,
            "exit_code": self.exit_code,
        }


def run_check(
    root: str,
    *,
    rules: Iterable[str] | None = None,
    baseline_path: str | None = None,
) -> CheckResult:
    """Scan ``root`` with the selected rules (default: all), apply
    pragmas + baseline, and return the partitioned findings."""
    t0 = time.monotonic()
    registry = all_rules()
    if rules is None:
        selected = list(registry.values())
    else:
        unknown = [r for r in rules if r not in registry]
        if unknown:
            raise ValueError(
                f"unknown rule(s) {unknown}; known: {sorted(registry)}"
            )
        selected = [registry[r] for r in rules]
    project = Project(root)
    result = CheckResult(
        rules_run=tuple(r.name for r in selected),
        modules_scanned=len(project.modules),
    )

    raw: list[Finding] = []
    for m in project.modules:
        if m.syntax_error:
            raw.append(
                Finding("parse", m.rel, 1, f"syntax error: {m.syntax_error}")
            )
    for rule in selected:
        raw.extend(rule.run(project))

    if baseline_path is None:
        candidate = os.path.join(project.root, BASELINE_NAME)
        baseline_path = candidate if os.path.isfile(candidate) else None
    baseline = load_baseline(baseline_path) if baseline_path else {}

    seen_keys = set()
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule, f.message)):
        seen_keys.add(f.key)
        mod = project._by_rel.get(f.path)
        if mod is not None and mod.allowed(f.rule, f.line):
            result.allowed += 1
        elif f.key in baseline:
            result.baselined.append(f)
        else:
            result.new.append(f)
    # Stale entries (fixed findings still baselined) are surfaced for
    # cleanup but never fail the check — a fix shouldn't force a
    # same-commit baseline edit.
    for key, reason in baseline.items():
        if key not in seen_keys:
            result.stale_baseline.append(
                {
                    "rule": key[0],
                    "path": key[1],
                    "message": key[2],
                    "reason": reason,
                }
            )
    result.runtime_s = time.monotonic() - t0
    return result


# ------------------------------------------------------- shared AST helpers
def call_name(node: ast.Call) -> str:
    """Dotted name of a call target: ``a.b.c(...)`` -> ``"a.b.c"``
    (non-name/attribute shapes -> ``""``)."""
    parts: list[str] = []
    cur: ast.expr = node.func
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return ""


def str_const(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def bytes_const(node: ast.AST) -> bytes | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, bytes):
        return node.value
    return None


def kwarg(node: ast.Call, name: str) -> ast.expr | None:
    for kw in node.keywords:
        if kw.arg == name:
            return kw.value
    return None


def self_attr(node: ast.AST) -> str | None:
    """``self.X`` -> ``"X"`` (anything else -> None)."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None
