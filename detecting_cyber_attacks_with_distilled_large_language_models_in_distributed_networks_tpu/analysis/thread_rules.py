"""Concurrency pass: cross-thread attribute writes need a held lock.

The threaded tiers (aggregation server + upload pool, serving scorer /
reader / writer threads, fault proxy, relay) share instance state
between a thread target and the methods other threads call. The GIL
makes single bytecodes atomic, not read-modify-writes: ``self.n += 1``
from two threads loses increments, ``self.d[k] += v`` likewise. The
pass encodes the house rule:

    An attribute written both from a ``threading.Thread`` /
    ``ThreadPoolExecutor`` target (or anything those targets call) and
    from any other method must have every write under a held lock, or
    carry ``# fedtpu: allow(unguarded): <reason>``.

Additionally, a read-modify-write (``+=``-style, attribute or
subscript) inside a method that runs CONCURRENTLY WITH ITSELF — a pool
``submit`` target, or a Thread target spawned inside a loop — is
flagged even with no second writer: N copies of the same method are
already a race.

What counts as "guarded": the write is lexically inside a ``with``
whose context expression's terminal name contains ``lock`` (``with
self._lock:``, ``with rnd.lock:``). What never counts as shared state:
attributes assigned a synchronization/queue object in ``__init__``
(Lock/RLock/Event/Condition/Semaphore/Queue/ThreadPoolExecutor) — they
synchronize themselves — and ``__init__`` writes themselves
(construction happens-before thread start).

Static limits, by design: guards are recognized lexically (a helper
that documents "caller holds the lock" needs a pragma), and reads are
not tracked (stale reads are real but drown the signal). The runtime
lock-order detector (:mod:`analysis.lockorder`) is the dynamic
complement: this pass says where a lock is missing, that one says when
the locks you do hold can deadlock.
"""

from __future__ import annotations

import ast
from typing import Iterator

from .core import Finding, Project, call_name, register, self_attr

RULE = "unguarded"

_SYNC_CTORS = frozenset(
    {
        "Lock",
        "RLock",
        "Event",
        "Condition",
        "Semaphore",
        "BoundedSemaphore",
        "Barrier",
        "Queue",
        "LifoQueue",
        "PriorityQueue",
        "SimpleQueue",
        "ThreadPoolExecutor",
    }
)

#: Method calls that mutate their receiver — a shared list/dict/set
#: mutated cross-thread races exactly like an assignment.
_MUTATORS = frozenset(
    {
        "append",
        "appendleft",
        "add",
        "update",
        "extend",
        "insert",
        "remove",
        "discard",
        "clear",
        "setdefault",
    }
)

_SPAWN_CALLS = ("Thread", "Timer")


class _Write:
    __slots__ = ("attr", "line", "method", "guarded", "rmw")

    def __init__(self, attr, line, method, guarded, rmw):
        self.attr = attr
        self.line = line
        self.method = method
        self.guarded = guarded
        self.rmw = rmw  # read-modify-write (augmented assignment)


class _ClassScan(ast.NodeVisitor):
    """Collect, for one class: self-attribute writes (with lexical
    lock-guard state), the self-method call graph, thread-entry
    methods, and which entries run concurrently with themselves."""

    def __init__(self):
        self.methods: set[str] = set()
        self.writes: list[_Write] = []
        self.calls: dict[str, set[str]] = {}
        self.entries: set[str] = set()
        self.concurrent_entries: set[str] = set()
        self.sync_attrs: set[str] = set()
        self._method: str | None = None
        self._guard_depth = 0
        self._loop_depth = 0

    # ------------------------------------------------------------- structure
    def scan(self, cls: ast.ClassDef) -> "_ClassScan":
        for node in cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods.add(node.name)
        for node in cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._method = node.name
                self.calls.setdefault(node.name, set())
                for stmt in node.body:
                    self.visit(stmt)
                self._method = None
        return self

    # ------------------------------------------------------------ traversal
    def visit_With(self, node: ast.With) -> None:
        guarded = any(
            self._is_lock_expr(item.context_expr) for item in node.items
        )
        if guarded:
            self._guard_depth += 1
        self.generic_visit(node)
        if guarded:
            self._guard_depth -= 1

    def _loop(self, node) -> None:
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = visit_AsyncFor = visit_While = _loop

    @staticmethod
    def _is_lock_expr(expr: ast.expr) -> bool:
        # `with self._lock:` / `with rnd.lock:` / bare `with lock:` —
        # the terminal name mentioning "lock" is the recognized guard.
        name = None
        if isinstance(expr, ast.Attribute):
            name = expr.attr
        elif isinstance(expr, ast.Name):
            name = expr.id
        elif isinstance(expr, ast.Call):
            # `with self._lock.acquire_timeout(...)` style helpers.
            return _ClassScan._is_lock_expr(expr.func)
        return name is not None and "lock" in name.lower()

    # --------------------------------------------------------------- writes
    def _record_target(self, target: ast.expr, rmw: bool) -> None:
        attr = self_attr(target)
        if attr is None and isinstance(target, ast.Subscript):
            attr = self_attr(target.value)
        if attr is None or self._method is None:
            return
        self.writes.append(
            _Write(attr, target.lineno, self._method, self._guard_depth > 0, rmw)
        )

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            targets = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for tt in targets:
                self._record_target(tt, rmw=False)
        if self._method == "__init__":
            self._note_sync_attr(node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_target(node.target, rmw=True)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_target(node.target, rmw=False)
        self.generic_visit(node)

    def _note_sync_attr(self, node: ast.Assign) -> None:
        if not isinstance(node.value, ast.Call):
            return
        ctor = call_name(node.value).rsplit(".", 1)[-1]
        if ctor in _SYNC_CTORS:
            for t in node.targets:
                attr = self_attr(t)
                if attr:
                    self.sync_attrs.add(attr)

    # ---------------------------------------------------------------- calls
    def visit_Call(self, node: ast.Call) -> None:
        if self._method is not None:
            callee = self_attr(node.func)
            if callee is not None:
                self.calls.setdefault(self._method, set()).add(callee)
            # Mutating method call on a self attribute == a write.
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS
            ):
                attr = self_attr(node.func.value)
                if attr is not None:
                    self.writes.append(
                        _Write(
                            attr,
                            node.lineno,
                            self._method,
                            self._guard_depth > 0,
                            False,
                        )
                    )
        target = call_name(node)
        tail = target.rsplit(".", 1)[-1]
        if tail in _SPAWN_CALLS or tail == "submit":
            spawned = self._spawned_methods(node)
            self.entries.update(spawned)
            if tail == "submit" or self._loop_depth > 0:
                # Pool targets and loop-spawned threads run concurrently
                # with themselves.
                self.concurrent_entries.update(spawned)
        self.generic_visit(node)

    def _spawned_methods(self, call: ast.Call) -> set[str]:
        """``self.X`` references anywhere in a Thread(...)/submit(...)
        call's arguments that name a method of this class — including
        through a lambda target."""
        out: set[str] = set()
        for sub in ast.walk(call):
            if sub is call.func:
                continue
            attr = self_attr(sub)
            if attr in self.methods:
                out.add(attr)
        return out


def _thread_side(scan: _ClassScan) -> set[str]:
    """Entry methods plus everything reachable from them through
    self-method calls."""
    seen: set[str] = set()
    frontier = list(scan.entries)
    while frontier:
        m = frontier.pop()
        if m in seen:
            continue
        seen.add(m)
        frontier.extend(scan.calls.get(m, ()))
    return seen


def _concurrent_side(scan: _ClassScan) -> set[str]:
    seen: set[str] = set()
    frontier = list(scan.concurrent_entries)
    while frontier:
        m = frontier.pop()
        if m in seen:
            continue
        seen.add(m)
        frontier.extend(scan.calls.get(m, ()))
    return seen


@register(
    RULE,
    "attributes written both from a thread target and another method "
    "must hold a lock; pool-concurrent read-modify-writes likewise",
)
def check_unguarded(project: Project) -> Iterator[Finding]:
    for m in project.modules:
        if m.tree is None:
            continue
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            scan = _ClassScan().scan(node)
            if not scan.entries:
                continue
            thread_side = _thread_side(scan)
            concurrent = _concurrent_side(scan)
            writes = [
                w
                for w in scan.writes
                if w.method not in ("__init__", "__new__")
                and w.attr not in scan.sync_attrs
            ]
            by_attr: dict[str, list[_Write]] = {}
            for w in writes:
                by_attr.setdefault(w.attr, []).append(w)
            for attr, ws in sorted(by_attr.items()):
                thread_writers = {w.method for w in ws if w.method in thread_side}
                other_writers = {
                    w.method for w in ws if w.method not in thread_side
                }
                cross = bool(thread_writers) and bool(
                    other_writers or len(thread_writers) > 1
                )
                for w in ws:
                    if w.guarded:
                        continue
                    if cross and w.method in thread_side | other_writers:
                        peers = sorted(
                            (thread_writers | other_writers) - {w.method}
                        ) or sorted(thread_writers)
                        yield Finding(
                            RULE,
                            m.rel,
                            w.line,
                            f"{node.name}.{attr} written without a held "
                            f"lock in {w.method}() while also written via "
                            f"{', '.join(p + '()' for p in peers)} on the "
                            "thread-target path",
                        )
                    elif w.rmw and w.method in concurrent:
                        yield Finding(
                            RULE,
                            m.rel,
                            w.line,
                            f"{node.name}.{attr} read-modify-write without "
                            f"a held lock in {w.method}(), which runs "
                            "concurrently with itself on the pool/thread "
                            "fan-out",
                        )
