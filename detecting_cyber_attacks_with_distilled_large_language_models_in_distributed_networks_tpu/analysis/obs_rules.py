"""Obs-vocabulary pass: the observability contracts stay closed.

Three cross-cutting vocabularies hold the obs layer together, and all
three are string-matched at runtime with no compiler in the loop:

``obs-span-vocab``
    Every span name emitted through a ``Tracer`` (``tracer.span(...)``,
    ``tracer.record(...)``, ``maybe_span(tracer, ...)``) must be a
    member of ``obs/trace.py``'s ``SPAN_NAMES`` tuple. The timeline
    tool groups by exact name; a typo'd or unregistered span silently
    falls out of every per-round attribution sum the tests pin to 10%
    of wall. The vocabulary is read from the SCANNED tree (not the
    imported package), so a mutated temp copy lints against its own
    contract.

``obs-metric-once``
    Metric families must be coherent: one name = one kind (a counter
    re-registered as a gauge raises at runtime — in whatever process
    first runs both paths), counters follow the ``*_total`` Prometheus
    convention the endpoint documents, and a family is registered from
    exactly one module (two tiers independently minting the same name
    will drift in help text and labels; share it from one place
    instead).

``bench-headline``
    Every headline field bench.py ASSERTS present (the
    ``[k for k in (...) if k not in rec]`` exit-3 pattern) must be
    produced somewhere (a dict-literal key or ``rec[...] =`` store in
    bench.py or the package). An asserted-but-never-produced field
    means the bench exits 3 on every run — this catches the rename
    half-done before the driver does.
"""

from __future__ import annotations

import ast
from typing import Iterator

from .core import Finding, Project, call_name, register, str_const

TRACE_REL = "obs/trace.py"
BENCH_REL = "bench.py"

_METRIC_KINDS = ("counter", "gauge", "histogram")


def _span_vocab(project: Project) -> tuple[frozenset[str], object] | None:
    trace = project.module(TRACE_REL)
    if trace is None or trace.tree is None:
        return None
    for node in trace.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "SPAN_NAMES"
            and isinstance(node.value, (ast.Tuple, ast.List))
        ):
            names = [str_const(e) for e in node.value.elts]
            if all(n is not None for n in names):
                return frozenset(names), trace
    return None


def _receiver_mentions_trace(func: ast.expr) -> bool:
    """True for ``tracer.span`` / ``self.tracer.record`` — the receiver
    chain's terminal name mentions "trace", which is what separates a
    Tracer call from any other ``.record()``/``.span()`` in the tree."""
    if not isinstance(func, ast.Attribute):
        return False
    recv = func.value
    name = ""
    if isinstance(recv, ast.Attribute):
        name = recv.attr
    elif isinstance(recv, ast.Name):
        name = recv.id
    return "trace" in name.lower()


@register(
    "obs-span-vocab",
    "every literal span name emitted through a Tracer is a member of "
    "obs/trace.py SPAN_NAMES",
)
def check_span_vocab(project: Project) -> Iterator[Finding]:
    got = _span_vocab(project)
    if got is None:
        yield Finding(
            "obs-span-vocab",
            TRACE_REL,
            1,
            "SPAN_NAMES tuple of string literals not found in "
            "obs/trace.py — the span-vocabulary pass has lost its anchor",
        )
        return
    vocab, _trace = got
    for m in project.modules:
        for node in m.walk():
            if not isinstance(node, ast.Call):
                continue
            name_arg: ast.expr | None = None
            if isinstance(node.func, ast.Attribute) and node.func.attr in (
                "span",
                "record",
            ):
                if _receiver_mentions_trace(node.func) and node.args:
                    name_arg = node.args[0]
            elif call_name(node).rsplit(".", 1)[-1] == "maybe_span":
                if len(node.args) >= 2:
                    name_arg = node.args[1]
            if name_arg is None:
                continue
            span = str_const(name_arg)
            if span is not None and span not in vocab:
                yield Finding(
                    "obs-span-vocab",
                    m.rel,
                    node.lineno,
                    f"span name {span!r} is not in obs/trace.py "
                    "SPAN_NAMES — the timeline tool will drop it from "
                    "every per-round attribution; add it to the "
                    "vocabulary (and the timeline docs) first",
                )


@register(
    "obs-metric-once",
    "metric names keep one kind, counters end _total, and each family "
    "is registered from exactly one module",
)
def check_metric_once(project: Project) -> Iterator[Finding]:
    # name -> {"kind": str, "modules": {rel: first line}}
    families: dict[str, dict] = {}
    registrations: list[tuple[str, str, str, int]] = []  # (name, kind, rel, line)
    for m in project.modules:
        if m.rel.endswith("obs/metrics.py"):
            continue  # the registry's own plumbing
        for node in m.walk():
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_KINDS
                and node.args
            ):
                continue
            name = str_const(node.args[0])
            if name is None:
                continue  # np.histogram(arr, ...) and friends
            registrations.append((name, node.func.attr, m.rel, node.lineno))
    for name, kind, rel, line in registrations:
        fam = families.setdefault(name, {"kind": kind, "modules": {}})
        if fam["kind"] != kind:
            yield Finding(
                "obs-metric-once",
                rel,
                line,
                f"metric {name!r} registered as {kind} here but as "
                f"{fam['kind']} elsewhere — the registry raises on the "
                "second registration at runtime",
            )
            continue
        fam["modules"].setdefault(rel, line)
        if kind == "counter" and not name.endswith("_total"):
            yield Finding(
                "obs-metric-once",
                rel,
                line,
                f"counter {name!r} does not end in '_total' — the "
                "Prometheus convention the /metrics endpoint documents",
            )
    for name, fam in sorted(families.items()):
        if len(fam["modules"]) > 1:
            mods = sorted(fam["modules"])
            rel = mods[1]
            yield Finding(
                "obs-metric-once",
                rel,
                fam["modules"][rel],
                f"metric {name!r} registered from multiple modules "
                f"({', '.join(mods)}) — help text and labels will drift; "
                "register it in one place and share the reference",
            )


@register(
    "bench-headline",
    "every headline field bench.py asserts present is actually "
    "produced by a record builder",
)
def check_bench_headline(project: Project) -> Iterator[Finding]:
    bench = project.module(BENCH_REL)
    if bench is None or bench.tree is None:
        return
    # Asserted: string constants S appearing in an `S not in X` compare
    # (the exit-3 missing-fields pattern) anywhere in bench.py, plus the
    # comprehension form where the iterated tuple holds the candidates.
    asserted: dict[str, int] = {}
    for node in bench.walk():
        if isinstance(node, ast.Compare) and len(node.ops) == 1 and isinstance(
            node.ops[0], ast.NotIn
        ):
            s = str_const(node.left)
            if s is not None:
                asserted.setdefault(s, node.lineno)
        elif isinstance(
            node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)
        ):
            # The `[k for k in (...) if k not in rec]` assert shape: a
            # comprehension over a literal tuple whose filter is NotIn.
            for gen in node.generators:
                if isinstance(gen.iter, (ast.Tuple, ast.List)) and any(
                    isinstance(cond, ast.Compare)
                    and len(cond.ops) == 1
                    and isinstance(cond.ops[0], ast.NotIn)
                    for cond in gen.ifs
                ):
                    for elt in gen.iter.elts:
                        v = str_const(elt)
                        if v is not None:
                            asserted.setdefault(v, elt.lineno)
    if not asserted:
        return
    # Produced: dict-literal keys and `X["k"] = ...` stores, bench.py +
    # package wide (records cross the module boundary via stats()/
    # timeline dicts).
    produced: set[str] = set()
    for m in project.modules:
        for node in m.walk():
            if isinstance(node, ast.Dict):
                for k in node.keys:
                    v = str_const(k) if k is not None else None
                    if v is not None:
                        produced.add(v)
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        v = str_const(t.slice)
                        if v is not None:
                            produced.add(v)
    for name, line in sorted(asserted.items()):
        if name not in produced:
            yield Finding(
                "bench-headline",
                bench.rel,
                line,
                f"bench.py asserts headline field {name!r} but nothing "
                "in bench.py or the package produces it — every run "
                "would exit 3 (half-done rename?)",
            )


