"""Determinism pass: the crc-contract modules must stay replayable.

The aggregation paths pin bit-exactness contracts: a streamed fold must
equal the barrier mean (comm/stream_agg.py), a depth-2 relay tree must
equal ``aggregate_tree``'s flat replay (comm/relay.py), same-seed
partitions must be identical across runs AND tiers (data/partition.py),
and a chaos campaign must replay byte-for-byte from its seed (faults/).
Every one of those contracts dies the moment wall-clock time, OS
entropy, or unseeded RNG state leaks into a value or an ordering — and
dies silently, as a crc mismatch in a live 256-client run instead of a
test failure.

``determinism`` flags, inside the contract modules only:

* ``time.time()`` / ``time.time_ns()`` — wall clock in a value path
  (``time.monotonic`` is exempt: durations don't feed folds);
* unseeded stdlib ``random.*`` calls (an explicitly constructed
  ``random.Random(seed)`` instance is fine — the rule matches the
  module, not instances);
* ``np.random.*`` convenience calls (the legacy global-state API);
  seeded constructors (``default_rng``/``Generator``/``Philox``/
  ``PCG64``/``SeedSequence``/``RandomState``) pass;
* ``os.urandom`` / ``uuid.uuid4`` / ``secrets.*`` — OS entropy;
* iterating directly over a ``set`` (literal, comprehension, or
  ``set()``/``frozenset()`` call) in a ``for`` or comprehension — set
  order is hash-randomized across processes, so a fold or partition
  driven by it diverges between the live run and its replay
  (``sorted(set(...))`` does not trigger: the sort re-pins the order).

Intentional uses stay, with a reviewed reason:
``# fedtpu: allow(determinism): <why this is not order/value-feeding>``
(e.g. span timestamps, nonce generation, fault-proxy wall-clock
throttling).
"""

from __future__ import annotations

import ast
from typing import Iterator

from .core import Finding, Project, call_name, register

#: The crc-contract surface (ISSUE 8): fold arithmetic, fold order,
#: partition assignment, the chaos layer's replayable plans, and the
#: FSDP shard-spec builders (parallel/mesh.py fsdp_dim/fsdp_spec must
#: pick the SAME shard layout on every process/round — the wire tier
#: scatters reply leaves onto specs it derives independently).
SCOPE = (
    "parallel/fedavg.py",
    "parallel/mesh.py",
    "comm/stream_agg.py",
    "comm/relay.py",
    "data/partition.py",
    "faults/",
    # Server aggregation strategies transform every round's global —
    # any nondeterminism here breaks the crc replay gate directly.
    "strategies/",
    # Wire-efficiency tier (ISSUE 17): the int8c quantize/dequant codec
    # and the batched fold engines both sit INSIDE the crc contract —
    # dequantization must replay bit-exactly and every fold engine must
    # match the ascending-id numpy accumulation bit-for-bit.
    "comm/quant.py",
    "ops/fold.py",
    # Delayed ground-truth plane (ISSUE 18): journal replay and the
    # scored-records join must rebuild bit-identical state from the
    # same files — timestamps are caller-supplied, never clock-read.
    "labels/",
    # Sharded scorer (ISSUE 20): the serving engine's bucket programs
    # and shard layout sit inside the crc contract too — a sharded
    # replica must replay the replicated engine's probs bit-for-bit
    # (bench's serve_fsdp_crc_exact), which any nondeterministic
    # bucketing/padding/placement choice here would break.
    "serving/engine.py",
)

_SEEDED_NP_CTORS = frozenset(
    {
        "default_rng",
        "Generator",
        "Philox",
        "PCG64",
        "PCG64DXSM",
        "MT19937",
        "SeedSequence",
        "RandomState",
    }
)

RULE = "determinism"


def _module_imports(module) -> set[str]:
    """Top-level module names bound by import statements (``random``,
    ``time``, ...), so ``random.shuffle`` from a local variable named
    ``random`` is not confused with the stdlib module."""
    names: set[str] = set()
    for node in module.walk():
        if isinstance(node, ast.Import):
            for alias in node.names:
                names.add(alias.asname or alias.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                names.add(alias.asname or alias.name)
    return names


def _flag_call(node: ast.Call, imports: set[str]) -> str | None:
    name = call_name(node)
    if not name:
        return None
    head = name.split(".", 1)[0]
    if name in ("time.time", "time.time_ns") and "time" in imports:
        return (
            f"{name}() is wall clock — a value/ordering input here breaks "
            "the replay contract (time.monotonic for durations)"
        )
    if head == "random" and "random" in imports:
        tail = name.rsplit(".", 1)[-1]
        if tail == "SystemRandom":
            return "random.SystemRandom is OS entropy — unreplayable"
        if tail in ("Random", "seed"):
            return None  # explicit instance construction / explicit seeding
        return (
            f"{name}() draws from the process-global unseeded RNG — use a "
            "seeded random.Random(seed) / np.random.default_rng(seed)"
        )
    if (
        name.startswith(("np.random.", "numpy.random."))
        and name.rsplit(".", 1)[-1] not in _SEEDED_NP_CTORS
    ):
        return (
            f"{name}() uses numpy's legacy global RNG state — construct a "
            "seeded generator (np.random.default_rng(seed)) instead"
        )
    if name == "os.urandom" and "os" in imports:
        return "os.urandom() is OS entropy — unreplayable by definition"
    if name in ("uuid.uuid4", "uuid.uuid1") and "uuid" in imports:
        return f"{name}() is OS-entropy-derived — unreplayable"
    if head == "secrets" and "secrets" in imports:
        return f"{name}() is OS entropy — unreplayable"
    return None


def _is_set_expr(node: ast.expr) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        return call_name(node) in ("set", "frozenset")
    return False


@register(
    RULE,
    "no wall clock / unseeded RNG / OS entropy / set-order iteration "
    "inside the crc-contract modules",
)
def check_determinism(project: Project) -> Iterator[Finding]:
    for m in project.select(SCOPE):
        imports = _module_imports(m)
        for node in m.walk():
            if isinstance(node, ast.Call):
                msg = _flag_call(node, imports)
                if msg:
                    yield Finding(RULE, m.rel, node.lineno, msg)
            iters: list[ast.expr] = []
            if isinstance(node, (ast.For, ast.AsyncFor)):
                iters.append(node.iter)
            elif isinstance(
                node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
            ):
                iters.extend(gen.iter for gen in node.generators)
            for it in iters:
                if _is_set_expr(it):
                    yield Finding(
                        RULE,
                        m.rel,
                        it.lineno,
                        "iteration directly over a set — hash-randomized "
                        "order feeding a fold/partition path diverges "
                        "between run and replay; iterate "
                        "sorted(...) instead",
                    )
