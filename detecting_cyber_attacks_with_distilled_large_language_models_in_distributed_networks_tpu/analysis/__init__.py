"""fedtpu check — invariant-aware static analysis for the federated tier.

The codebase's correctness rests on hand-maintained invariants that no
type checker sees: disjoint HMAC domains per frame/direction in
comm/wire.py, crc-bit-exact pinned fold order in the aggregation paths,
seeded-only randomness in the chaos/partition layers, a closed span
vocabulary in obs/trace.py, and lock discipline across the threaded
server/serving tiers. This package encodes those contracts as AST
passes (``fedtpu check``) plus a runtime lock-order cycle detector
armed in the test fast lane (:mod:`analysis.lockorder`).

Layout:

* :mod:`analysis.core` — the pass framework: :class:`~.core.Rule`,
  :class:`~.core.Finding`, project scanning, per-line
  ``# fedtpu: allow(<rule>)`` pragmas, the reviewed
  ``ANALYSIS_BASELINE.json``, and :func:`~.core.run_check`.
* :mod:`analysis.wire_rules` — wire-domain pass (magic/domain
  uniqueness + coverage, explicit stream ``direction=``).
* :mod:`analysis.determinism_rules` — determinism pass over the
  crc-contract modules (fold/partition order must be seeded and
  reproducible).
* :mod:`analysis.thread_rules` — concurrency pass (cross-thread
  attribute writes must be lock-guarded or pragma'd).
* :mod:`analysis.obs_rules` — obs-vocabulary pass (span names ⊆
  SPAN_NAMES, consistent metric registration, bench headline fields
  actually produced).
* :mod:`analysis.lockorder` — runtime lock-order detector (a
  ``threading.Lock``/``RLock`` wrapper building a per-creation-site
  acquisition graph; cycles = deadlock risk).
"""

from .core import (  # noqa: F401
    CheckResult,
    Finding,
    Rule,
    Project,
    all_rules,
    load_baseline,
    run_check,
)
