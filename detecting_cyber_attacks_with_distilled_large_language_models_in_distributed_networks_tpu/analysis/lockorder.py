"""Runtime lock-order cycle detector — the dynamic half of the
concurrency pass.

The static pass (:mod:`analysis.thread_rules`) says where a lock is
missing; this module says when the locks you DO hold can deadlock. It
wraps ``threading.Lock``/``threading.RLock`` so every successful
acquire records an edge ``held-lock -> acquired-lock`` in a global
digraph over lock INSTANCES (monotonic uids, never recycled). A cycle
in that graph means two threads took the same two locks in opposite
orders: the classic ABBA deadlock, latent until the interleaving is
unlucky — exactly the class a 256-client round flushes out in
production and a unit test never does. Instances — not creation sites
— are the nodes on purpose: one line can create several distinct locks
(CPython's ``ThreadPoolExecutor.__init__`` makes ``_shutdown_lock``
and the idle semaphore's inner lock back to back, and ``submit``
chains shutdown→global→semaphore), and a site-aggregated graph reports
that as a cycle no real schedule can deadlock on. For the REPORT,
edges and cycles are rendered by creation site (``file:line``) — the
human-actionable identity.

Armed in the pytest fast lane (tests/conftest.py patches the factories
for the whole session and fails it on any cycle; ``FEDTPU_LOCKORDER=0``
disarms). Only locks created by repo code are tracked — the factory
walks a few stack frames and hands stdlib-internal creations the
original primitive untouched, so the interpreter's own locking stays
invisible and free.

Same-site NESTING (holding one instance while acquiring another from
the same creation line — per-round locks, per-client locks) is
additionally counted in ``same_site_edges``: consistent-order nesting
is often ordered by construction (ascending client id) and only a
human can tell, so it is surfaced, not failed. If two same-site
instances are ever taken in OPPOSITE orders, that is an instance-level
cycle like any other and fails the session.

Standalone use (tests, notebooks)::

    det = LockOrderDetector()
    a, b = det.lock("a"), det.lock("b")
    ... acquire in both orders from two threads ...
    assert det.report().cycles
"""

from __future__ import annotations

import os
import sys
import threading
from dataclasses import dataclass, field

_THIS_DIR = os.path.dirname(os.path.abspath(__file__))
#: Default tracked tree: the package directory (lockorder's parent's
#: parent is the package root).
PACKAGE_DIR = os.path.dirname(_THIS_DIR)


@dataclass
class LockOrderReport:
    """Session summary: the acquisition-order digraph + its analysis."""

    edges: dict[tuple[str, str], int] = field(default_factory=dict)
    #: site -> how many tracked lock instances were created there
    sites: dict[str, int] = field(default_factory=dict)
    cycles: list[list[str]] = field(default_factory=list)
    same_site_edges: dict[str, int] = field(default_factory=dict)
    acquisitions: int = 0

    def render(self) -> str:
        lines = [
            f"lock-order: {len(self.sites)} tracked site(s), "
            f"{self.acquisitions} acquisition(s), "
            f"{len(self.edges)} order edge(s)"
        ]
        for cyc in self.cycles:
            lines.append(
                "  CYCLE (ABBA deadlock risk): " + " -> ".join(cyc + cyc[:1])
            )
        for site, n in sorted(self.same_site_edges.items()):
            lines.append(
                f"  same-site nesting at {site} ({n}x) — safe only if "
                "instances are acquired in a pinned order"
            )
        return "\n".join(lines)


class LockOrderDetector:
    """Collects acquisition-order edges from :class:`_TrackedLock`s.

    Edges are recorded between lock INSTANCES (monotonic uids — never
    recycled, unlike ``id()``), and only aggregated up to creation
    sites for display. Site-level cycle detection would invent ABBA
    where none exists: one creation line can host several distinct
    locks (CPython's own ``ThreadPoolExecutor.__init__`` makes
    ``_shutdown_lock`` AND the idle semaphore's inner lock on adjacent
    lines; ``submit`` orders shutdown→global→semaphore — a site-graph
    "cycle" spanning three different locks that can never deadlock).
    An instance-level cycle IS a real opposite-order proof, including
    between same-site instances (two rounds' locks taken in reversed
    orders), so those fail too."""

    def __init__(self) -> None:
        self._graph_lock = threading.Lock()  # guards the dicts below
        self._edges: dict[tuple[int, int], int] = {}  # uid digraph
        self._uid_site: dict[int, str] = {}
        self._sites: dict[str, int] = {}
        self._same_site: dict[str, int] = {}
        self._acquisitions = 0
        self._next_uid = 0
        # Held stacks keyed by thread id, NOT threading.local: a
        # threading.Lock may legally be released by a different thread
        # than its acquirer (handoff patterns), and a thread-local
        # stack would keep the stale entry forever — every later
        # acquire in the acquirer's thread would then record phantom
        # edges, and one matching reverse edge turns into a fabricated
        # ABBA cycle failing the session.
        self._held_by_thread: dict[int, list] = {}

    # ---------------------------------------------------------- construction
    def lock(self, site: str | None = None):
        """A tracked ``threading.Lock`` (tests name the site)."""
        return _TrackedLock(self, threading.Lock, site or _caller_site())

    def rlock(self, site: str | None = None):
        return _TrackedLock(self, threading.RLock, site or _caller_site())

    def _register(self, site: str) -> int:
        with self._graph_lock:
            self._sites[site] = self._sites.get(site, 0) + 1
            self._next_uid += 1
            self._uid_site[self._next_uid] = site
            return self._next_uid

    # ------------------------------------------------------------- recording
    def _on_acquired(self, lock: "_TrackedLock", *, record_edges: bool) -> None:
        tid = threading.get_ident()
        with self._graph_lock:
            held = self._held_by_thread.setdefault(tid, [])
            reentrant = any(entry[0] is lock for entry in held)
            if record_edges and not reentrant:
                self._acquisitions += 1
                for prior, prior_site in held:
                    if prior is lock:
                        continue
                    key = (prior.uid, lock.uid)
                    self._edges[key] = self._edges.get(key, 0) + 1
                    if prior_site == lock.site:
                        self._same_site[lock.site] = (
                            self._same_site.get(lock.site, 0) + 1
                        )
            held.append((lock, lock.site))

    def _on_released(self, lock: "_TrackedLock") -> None:
        tid = threading.get_ident()
        with self._graph_lock:
            # The releasing thread's stack first (the overwhelmingly
            # common case), then every other thread's — a cross-thread
            # release must clear the ACQUIRER's entry or it pollutes
            # that thread's ordering context forever.
            stacks = [tid] + [t for t in self._held_by_thread if t != tid]
            for t in stacks:
                held = self._held_by_thread.get(t)
                if not held:
                    continue
                for i in range(len(held) - 1, -1, -1):
                    if held[i][0] is lock:
                        del held[i]
                        if not held:
                            del self._held_by_thread[t]
                        return

    # --------------------------------------------------------------- analysis
    def report(self) -> LockOrderReport:
        with self._graph_lock:
            uid_edges = dict(self._edges)
            uid_site = dict(self._uid_site)
            sites = dict(self._sites)
            same = dict(self._same_site)
            acq = self._acquisitions
        site_edges: dict[tuple[str, str], int] = {}
        for (a, b), n in uid_edges.items():
            key = (uid_site[a], uid_site[b])
            site_edges[key] = site_edges.get(key, 0) + n
        cycles = [
            [uid_site[u] for u in cyc] for cyc in _find_cycles(uid_edges)
        ]
        return LockOrderReport(
            edges=site_edges,
            sites=sites,
            cycles=cycles,
            same_site_edges=same,
            acquisitions=acq,
        )


def _find_cycles(edges: dict[tuple[int, int], int]) -> list[list[int]]:
    """Strongly connected components with >1 node (Tarjan, iterative)
    over the lock-INSTANCE digraph. Self-edges never exist (reentrant
    acquires are filtered), so every multi-node SCC is a genuine
    opposite-order cycle."""
    adj: dict[int, list[int]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
        adj.setdefault(b, [])
    index: dict[int, int] = {}
    low: dict[int, int] = {}
    on_stack: set[int] = set()
    stack: list[int] = []
    counter = [0]
    sccs: list[list[int]] = []

    for root in sorted(adj):
        if root in index:
            continue
        work = [(root, iter(adj[root]))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(adj[nxt])))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1:
                    sccs.append(sorted(scc))
    return sccs


class _TrackedLock:
    """Lock/RLock wrapper feeding a :class:`LockOrderDetector`.

    Implements the ``Condition`` interplay surface explicitly
    (``_release_save``/``_acquire_restore``/``_is_owned``) so a
    ``Condition.wait`` keeps the held-stack accurate: the save pops,
    the restore pushes WITHOUT recording edges (a post-wait re-acquire
    is a scheduling event, not an ordering decision)."""

    def __init__(self, detector: LockOrderDetector, factory, site: str):
        self._inner = factory()
        self._det = detector
        self.site = site
        self.uid = detector._register(site)

    # ------------------------------------------------------------- lock API
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._det._on_acquired(self, record_edges=True)
        return got

    def release(self) -> None:
        self._inner.release()
        self._det._on_released(self)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # --------------------------------------------- Condition interplay (RLock)
    def __getattr__(self, name: str):
        # ``Condition.__init__`` binds ``_release_save``/
        # ``_acquire_restore``/``_is_owned`` via attribute access inside
        # try/except AttributeError — a plain Lock must NOT expose them
        # (the fallback path uses acquire/release, which we track), so
        # they are resolved dynamically: present exactly when the inner
        # primitive has them, wrapped to keep the held-stack accurate
        # across a wait (the restore records no edges — a post-wait
        # re-acquire is a scheduling event, not an ordering decision).
        if name == "_release_save":
            inner = self._inner._release_save  # AttributeError on Lock

            def _release_save():
                state = inner()
                self._det._on_released(self)
                return state

            return _release_save
        if name == "_acquire_restore":
            inner = self._inner._acquire_restore

            def _acquire_restore(state):
                inner(state)
                self._det._on_acquired(self, record_edges=False)

            return _acquire_restore
        # Everything else (``_is_owned``, ``_at_fork_reinit``, future
        # internals) delegates straight to the wrapped primitive.
        return getattr(self._inner, name)

    def __repr__(self) -> str:
        return f"<TrackedLock site={self.site} {self._inner!r}>"


# --------------------------------------------------------------- global arm
_ARMED: dict | None = None


def _caller_site() -> str:
    """First stack frame outside this module, as ``relpath:lineno``."""
    frame = sys._getframe(1)
    for _ in range(24):
        if frame is None:
            break
        fn = frame.f_code.co_filename
        if fn != __file__:
            return f"{_relsite(fn)}:{frame.f_lineno}"
        frame = frame.f_back
    return "<unknown>:0"


def _relsite(path: str) -> str:
    root = os.path.dirname(PACKAGE_DIR)
    try:
        return os.path.relpath(path, root).replace(os.sep, "/")
    except ValueError:
        return path


def _repo_site(paths: tuple[str, ...]) -> str | None:
    """Nearest stack frame inside one of ``paths`` (skipping this
    module), or None — the factory's tracked/untracked decision. The
    walk looks THROUGH stdlib frames (dataclasses ``default_factory``,
    ``queue.Queue.__init__``) so locks the repo creates indirectly are
    still attributed to the repo line that caused them."""
    frame = sys._getframe(2)
    for _ in range(16):
        if frame is None:
            return None
        fn = frame.f_code.co_filename
        if fn != __file__ and any(fn.startswith(p) for p in paths):
            return f"{_relsite(fn)}:{frame.f_lineno}"
        frame = frame.f_back
    return None


def arm(paths: tuple[str, ...] | None = None) -> LockOrderDetector:
    """Patch ``threading.Lock``/``RLock`` with tracked factories for
    locks created (directly or transitively) by code under ``paths``
    (default: the fedtpu package). Idempotent; :func:`disarm` restores.
    """
    global _ARMED
    if _ARMED is not None:
        return _ARMED["detector"]
    det = LockOrderDetector()
    tracked_paths = tuple(paths) if paths else (PACKAGE_DIR,)
    orig_lock, orig_rlock = threading.Lock, threading.RLock

    def make_lock():  # noqa: ANN202 - threading factory signature
        site = _repo_site(tracked_paths)
        if site is None:
            return orig_lock()
        return _TrackedLock(det, orig_lock, site)

    def make_rlock():
        site = _repo_site(tracked_paths)
        if site is None:
            return orig_rlock()
        return _TrackedLock(det, orig_rlock, site)

    threading.Lock = make_lock
    threading.RLock = make_rlock
    _ARMED = {
        "detector": det,
        "orig": (orig_lock, orig_rlock),
    }
    return det


def disarm() -> LockOrderReport | None:
    """Restore the original factories and return the session report
    (None when not armed)."""
    global _ARMED
    if _ARMED is None:
        return None
    threading.Lock, threading.RLock = _ARMED["orig"]
    det = _ARMED["detector"]
    _ARMED = None
    return det.report()


def armed_detector() -> LockOrderDetector | None:
    return _ARMED["detector"] if _ARMED is not None else None
