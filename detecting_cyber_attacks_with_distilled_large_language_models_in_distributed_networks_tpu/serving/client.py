"""Scoring-service SDK + load generator (shared by tests and bench.py).

Three client shapes over the same wire:

* :class:`ScoringClient` — one TCP connection, synchronous
  request/reply (``score()``); concurrency comes from many clients —
  which is what makes the server's micro-batcher earn its keep: N
  concurrent connections coalesce into one padded bucket dispatch.
* :class:`PipelinedScoringClient` — multi-request pipelining on ONE
  connection: ``submit()`` returns a future immediately and a reader
  thread matches replies to pending requests by the protocol's id echo.
  Replies may arrive out of order (a deadline reject overtakes scoring;
  a router fans one connection across replicas), which is exactly why
  the wire carries ids instead of relying on ordering.
* :class:`AsyncScoringClient` — the asyncio variant of the pipelined
  shape: ``await score(...)`` from any number of concurrent tasks on
  one connection, no threads.

:func:`run_load` drives a service with any of them (closed-loop threads,
optional pipelining depth, optional open-loop pacing at a target QPS)
and reports client-observed throughput and latency percentiles — the
numbers bench.py publishes.
"""

from __future__ import annotations

import socket
import threading
import time
from concurrent.futures import Future
from typing import Any, Mapping, Sequence

import numpy as np

from ..comm import framing
from ..comm.wire import NONCE_LEN, NONCE_MAGIC, WireError
from . import protocol


class ScoreRejected(Exception):
    """Explicit server-side refusal (admission control / deadline)."""

    def __init__(self, code: int, reason: str, req_id: int):
        super().__init__(f"request {req_id} rejected ({code}): {reason}")
        self.code = int(code)
        self.reason = reason
        self.req_id = int(req_id)


def _set_nodelay(sock: socket.socket) -> None:
    """Disable Nagle on a scoring socket: the frames are small and the
    transport writes header + payload separately (write-write-read), a
    pattern Nagle + delayed ACK turns into per-frame stalls — visibly so
    once a router hop doubles the TCP legs per request."""
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass


def answer_auth_challenge(sock: socket.socket, auth_key: bytes) -> None:
    """Client side of the scoring port's HMAC handshake: read the
    server's NONCE challenge, answer with the keyed proof. Shared by
    every client shape here AND the router's backend dials — the
    handshake must not exist four times and drift."""
    try:
        chal = bytes(framing.recv_frame(sock, send_ack=False))
    except (OSError, ConnectionError) as e:
        raise WireError(
            "server sent no auth challenge — is it running with "
            f"--auth? ({e})"
        ) from None
    if len(chal) != len(NONCE_MAGIC) + NONCE_LEN or not chal.startswith(
        NONCE_MAGIC
    ):
        raise WireError(
            f"bad auth challenge from server (magic {chal[:4]!r})"
        )
    framing.send_frame(
        sock,
        protocol.build_auth_response(auth_key, chal[len(NONCE_MAGIC) :]),
        await_ack=False,
    )


class ScoringClient:
    """Blocking scoring connection. Not thread-safe; one per thread.

    ``auth_key``: the scoring port's shared secret (server ``--auth``):
    the constructor answers the server's per-connection nonce challenge
    before the first request. Against a server that requires auth, a
    keyless client fails with a clear WireError on its first score()
    (the challenge frame arrives where the reply was expected)."""

    def __init__(
        self,
        host: str,
        port: int,
        *,
        timeout: float = 30.0,
        auth_key: bytes | None = None,
    ):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.settimeout(timeout)
        _set_nodelay(self.sock)
        self._next_id = 0
        if auth_key is not None:
            try:
                answer_auth_challenge(self.sock, auth_key)
            except WireError:
                self.close()
                raise

    def score(
        self,
        *,
        text: str | None = None,
        features: Mapping[str, Any] | None = None,
        deadline_ms: float | None = None,
        trace: str | None = None,
    ) -> dict:
        """Score one flow; returns the reply dict (prob, prediction,
        round, batch_size, bucket, queue_ms — plus ``trace`` echoed when
        the request carried one). Raises :class:`ScoreRejected` on an
        explicit reject frame."""
        self._next_id += 1
        req_id = self._next_id
        framing.send_frame(
            self.sock,
            protocol.build_request(
                req_id,
                text=text,
                features=features,
                deadline_ms=deadline_ms,
                trace=trace,
            ),
            await_ack=False,
        )
        reply = bytes(framing.recv_frame(self.sock, send_ack=False))
        if reply[:4] == NONCE_MAGIC:
            # The server's auth challenge landed where the reply was
            # expected: this client connected without a key to an
            # --auth server. Name the fix instead of a generic magic error.
            raise WireError(
                "server requires authentication — construct the client "
                "with auth_key (server runs with --auth)"
            )
        if protocol.is_reject(reply):
            body = protocol.parse_reject(reply)
            raise ScoreRejected(body["code"], body["reason"], body["id"])
        body = protocol.parse_reply(reply)
        if body["id"] != req_id:
            raise WireError(
                f"reply for request {body['id']} arrived while awaiting "
                f"{req_id} (synchronous client; server must answer in order)"
            )
        return body

    def stats(self) -> dict:
        """Fetch the server's ``stats()`` snapshot over this connection
        (the in-band probe the router's health checks ride)."""
        self._next_id += 1
        req_id = self._next_id
        framing.send_frame(
            self.sock, protocol.build_stats_request(req_id), await_ack=False
        )
        body = protocol.parse_stats_reply(
            bytes(framing.recv_frame(self.sock, send_ack=False))
        )
        if body["id"] != req_id:
            raise WireError(
                f"stats reply for request {body['id']} arrived while "
                f"awaiting {req_id}"
            )
        return body["stats"]

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def __enter__(self) -> "ScoringClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class PipelinedScoringClient:
    """Multi-request pipelining on one connection.

    ``submit()`` sends immediately and returns a
    :class:`concurrent.futures.Future`; a reader thread matches replies
    to pending requests by the protocol's id echo, so any number of
    requests ride the wire concurrently and out-of-order replies (a
    deadline reject overtaking scoring, a router fanning one connection
    across replicas) resolve correctly. Thread-safe: any thread may
    submit. A rejected request resolves its future with
    :class:`ScoreRejected`; a dead connection fails every pending future
    with the underlying error."""

    def __init__(
        self,
        host: str,
        port: int,
        *,
        timeout: float = 30.0,
        auth_key: bytes | None = None,
    ):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.settimeout(timeout)
        _set_nodelay(self.sock)
        if auth_key is not None:
            try:
                answer_auth_challenge(self.sock, auth_key)
            except WireError:
                self.close()
                raise
        self._lock = threading.Lock()  # pending map + id counter + _err
        self._wlock = threading.Lock()  # serializes frame writes
        self._pending: dict[int, Future] = {}
        self._next_id = 0
        self._err: Exception | None = None
        self._closed = False
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    # ------------------------------------------------------------ submit
    def submit(
        self,
        *,
        text: str | None = None,
        features: Mapping[str, Any] | None = None,
        deadline_ms: float | None = None,
        trace: str | None = None,
    ) -> Future:
        with self._lock:
            if self._err is not None:
                raise self._err
            self._next_id += 1
            req_id = self._next_id
            fut: Future = Future()
            self._pending[req_id] = fut
        frame = protocol.build_request(
            req_id,
            text=text,
            features=features,
            deadline_ms=deadline_ms,
            trace=trace,
        )
        try:
            with self._wlock:
                framing.send_frame(self.sock, frame, await_ack=False)
        except (OSError, ConnectionError) as e:
            with self._lock:
                self._pending.pop(req_id, None)
            # The reader may have raced us to the dead socket and failed
            # this future via _fail_all already — never double-resolve.
            if not fut.done():
                fut.set_exception(WireError(f"send failed: {e}"))
        return fut

    def score(self, *, timeout: float | None = None, **kw) -> dict:
        """Synchronous convenience over :meth:`submit` (one in flight)."""
        return self.submit(**kw).result(timeout=timeout)

    # ------------------------------------------------------------- reader
    def _read_loop(self) -> None:
        while True:
            try:
                frame = bytes(
                    framing.recv_frame(self.sock, send_ack=False)
                )
            except (OSError, ConnectionError, WireError) as e:
                self._fail_all(
                    e
                    if isinstance(e, WireError)
                    else WireError(f"connection lost: {e}")
                )
                return
            if frame[:4] == NONCE_MAGIC:
                self._fail_all(
                    WireError(
                        "server requires authentication — construct the "
                        "client with auth_key (server runs with --auth)"
                    )
                )
                return
            try:
                req_id = protocol.frame_id(frame)
            except WireError as e:
                self._fail_all(e)
                return
            with self._lock:
                fut = self._pending.pop(req_id, None)
            if fut is None:
                continue  # reply for a send that already failed locally
            try:
                if protocol.is_reject(frame):
                    body = protocol.parse_reject(frame)
                    fut.set_exception(
                        ScoreRejected(body["code"], body["reason"], body["id"])
                    )
                elif protocol.is_stats_reply(frame):
                    fut.set_result(protocol.parse_stats_reply(frame)["stats"])
                else:
                    fut.set_result(protocol.parse_reply(frame))
            except WireError as e:
                fut.set_exception(e)

    def _fail_all(self, err: Exception) -> None:
        with self._lock:
            if self._closed:
                err = WireError("client closed")
            self._err = err
            pending = list(self._pending.values())
            self._pending.clear()
        for fut in pending:
            if not fut.done():
                fut.set_exception(err)

    # ---------------------------------------------------------------- misc
    def stats(self, *, timeout: float | None = None) -> dict:
        """The server's ``stats()`` snapshot, pipelined like any request."""
        with self._lock:
            if self._err is not None:
                raise self._err
            self._next_id += 1
            req_id = self._next_id
            fut: Future = Future()
            self._pending[req_id] = fut
        try:
            with self._wlock:
                framing.send_frame(
                    self.sock,
                    protocol.build_stats_request(req_id),
                    await_ack=False,
                )
        except (OSError, ConnectionError) as e:
            with self._lock:
                self._pending.pop(req_id, None)
            raise WireError(f"send failed: {e}") from None
        return fut.result(timeout=timeout)

    def close(self) -> None:
        with self._lock:
            self._closed = True
        try:
            # shutdown() BEFORE close(): a plain close while the reader
            # blocks in recv is deferred by CPython until the recv
            # returns (the faults/proxy.py lesson) — the reader would
            # sit its full socket timeout out and stall this join.
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        self._reader.join(timeout=5.0)

    def __enter__(self) -> "PipelinedScoringClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class AsyncScoringClient:
    """asyncio scoring client: ``await score(...)`` from any number of
    concurrent tasks over one connection.

    The async twin of :class:`PipelinedScoringClient` — same id-matched
    pipelining, no threads: a reader task resolves per-request futures
    as frames arrive. Framing is re-implemented on asyncio streams in
    fire-and-forget mode (``await_ack=False`` both directions, exactly
    the sync protocol), including the CRC check — the transport contract
    must not weaken because the caller went async.

    Construct with ``await AsyncScoringClient.connect(host, port)``.
    """

    def __init__(self, reader, writer):
        self._reader = reader
        self._writer = writer
        self._pending: dict[int, Any] = {}  # id -> asyncio.Future
        self._next_id = 0
        self._err: Exception | None = None
        self._reader_task = None

    # -------------------------------------------------------------- framing
    async def _recv_frame(self) -> bytes:
        import struct

        from ..comm import native

        header = await self._reader.readexactly(len(framing.FRAME_MAGIC) + 12)
        if header[:4] != framing.FRAME_MAGIC:
            raise WireError(f"bad frame magic {bytes(header[:4])!r}")
        length, crc = struct.unpack("<QI", header[4:])
        if length > framing.MAX_FRAME:
            raise WireError(f"frame length {length} exceeds {framing.MAX_FRAME}")
        payload = await self._reader.readexactly(length)
        if native.crc32(payload) != crc:
            raise WireError("frame CRC mismatch")
        return bytes(payload)

    async def _send_frame(self, payload: bytes) -> None:
        import struct

        from ..comm import native

        self._writer.write(
            framing.FRAME_MAGIC
            + struct.pack("<QI", len(payload), native.crc32(payload))
            + payload
        )
        await self._writer.drain()

    # ------------------------------------------------------------- lifecycle
    @classmethod
    async def connect(
        cls,
        host: str,
        port: int,
        *,
        auth_key: bytes | None = None,
    ) -> "AsyncScoringClient":
        import asyncio

        reader, writer = await asyncio.open_connection(host, port)
        self = cls(reader, writer)
        if auth_key is not None:
            chal = await self._recv_frame()
            if len(chal) != len(NONCE_MAGIC) + NONCE_LEN or not chal.startswith(
                NONCE_MAGIC
            ):
                writer.close()
                raise WireError(
                    f"bad auth challenge from server (magic {chal[:4]!r})"
                )
            await self._send_frame(
                protocol.build_auth_response(
                    auth_key, chal[len(NONCE_MAGIC) :]
                )
            )
        self._reader_task = asyncio.ensure_future(self._read_loop())
        return self

    async def _read_loop(self) -> None:
        import asyncio

        try:
            while True:
                frame = await self._recv_frame()
                if frame[:4] == NONCE_MAGIC:
                    raise WireError(
                        "server requires authentication — connect with "
                        "auth_key (server runs with --auth)"
                    )
                req_id = protocol.frame_id(frame)
                fut = self._pending.pop(req_id, None)
                if fut is None or fut.done():
                    continue
                if protocol.is_reject(frame):
                    body = protocol.parse_reject(frame)
                    fut.set_exception(
                        ScoreRejected(body["code"], body["reason"], body["id"])
                    )
                elif protocol.is_stats_reply(frame):
                    fut.set_result(protocol.parse_stats_reply(frame)["stats"])
                else:
                    fut.set_result(protocol.parse_reply(frame))
        except asyncio.CancelledError:
            # close() cancelled us: awaiters blocked in score()/stats()
            # must not hang forever on futures nobody will resolve.
            self._fail_pending(WireError("client closed"))
            raise
        except (OSError, ConnectionError, WireError, EOFError) as e:
            self._fail_pending(
                e
                if isinstance(e, WireError)
                else WireError(f"connection lost: {e}")
            )

    def _fail_pending(self, err: Exception) -> None:
        self._err = err
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(err)
        self._pending.clear()

    async def score(
        self,
        *,
        text: str | None = None,
        features: Mapping[str, Any] | None = None,
        deadline_ms: float | None = None,
        trace: str | None = None,
    ) -> dict:
        """Score one flow; safe to call from many tasks concurrently —
        requests pipeline on the single connection and replies match by
        id. Raises :class:`ScoreRejected` on an explicit reject."""
        import asyncio

        if self._err is not None:
            raise self._err
        self._next_id += 1
        req_id = self._next_id
        fut = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        try:
            await self._send_frame(
                protocol.build_request(
                    req_id,
                    text=text,
                    features=features,
                    deadline_ms=deadline_ms,
                    trace=trace,
                )
            )
        except BaseException:
            self._pending.pop(req_id, None)  # never leak the entry
            raise
        return await fut

    async def stats(self) -> dict:
        import asyncio

        if self._err is not None:
            raise self._err
        self._next_id += 1
        req_id = self._next_id
        fut = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        try:
            await self._send_frame(protocol.build_stats_request(req_id))
        except BaseException:
            self._pending.pop(req_id, None)  # never leak the entry
            raise
        return await fut

    async def close(self) -> None:
        if self._reader_task is not None:
            self._reader_task.cancel()
            try:
                await self._reader_task
            except BaseException:
                pass
        self._fail_pending(WireError("client closed"))
        try:
            self._writer.close()
            await self._writer.wait_closed()
        except (OSError, ConnectionError):
            pass

    async def __aenter__(self) -> "AsyncScoringClient":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()


def fetch_stats(
    host: str,
    port: int,
    *,
    timeout: float = 10.0,
    auth_key: bytes | None = None,
) -> dict:
    """One-shot ``stats()`` fetch: dial, (auth,) probe, close. The ops
    convenience behind ``fedtpu route``'s status logging and tests."""
    with ScoringClient(
        host, port, timeout=timeout, auth_key=auth_key
    ) as cli:
        return cli.stats()


def probe_scores(
    host: str,
    port: int,
    texts: Sequence[str],
    *,
    timeout: float = 10.0,
    deadline_ms: float | None = None,
    trace: str | None = None,
    auth_key: bytes | None = None,
) -> list[tuple[dict, float]]:
    """One canary pass: dial ONE connection, score every text in order,
    close. Returns ``(reply, latency_s)`` per text, where the latency is
    the per-request send->reply wall — the sentinel's end-to-end canary
    measurement (obs/sentinel.py), deliberately the synchronous client
    so each probe measures a full round trip, not pipelined overlap. An
    explicit server reject still yields a measurement: the reply dict is
    the reject body plus ``"rejected": True`` (a canary that cannot be
    scored is a finding, not a crash); transport errors propagate to the
    caller, who counts the pass unreachable."""
    out: list[tuple[dict, float]] = []
    with ScoringClient(
        host, port, timeout=timeout, auth_key=auth_key
    ) as cli:
        for text in texts:
            t0 = time.monotonic()
            try:
                reply = cli.score(
                    text=text, deadline_ms=deadline_ms, trace=trace
                )
            except ScoreRejected as e:
                reply = {
                    "id": e.req_id,
                    "rejected": True,
                    "code": e.code,
                    "reason": e.reason,
                    "prob": float("nan"),
                    "prediction": 0,
                    "round": None,
                }
            out.append((reply, time.monotonic() - t0))
    return out


def load_arrival_trace(path: str) -> list[float]:
    """Read a recorded inter-arrival trace: one non-negative gap (in
    seconds) per line, blank lines and ``#`` comments skipped. The
    bench fixtures ship a tiny bursty trace in this format."""
    gaps: list[float] = []
    with open(path) as f:
        for raw in f:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            gaps.append(float(line))
    if not gaps:
        raise ValueError(f"arrival trace {path!r} has no gaps")
    if any(g < 0.0 for g in gaps):
        raise ValueError(f"arrival trace {path!r} has negative gaps")
    return gaps


def run_load(
    host: str,
    port: int,
    texts: Sequence[str],
    *,
    concurrency: int = 4,
    requests: int | None = None,
    deadline_ms: float | None = None,
    timeout: float = 60.0,
    auth_key: bytes | None = None,
    pipeline: int = 1,
    target_qps: float | None = None,
    arrival_trace: Sequence[float] | None = None,
) -> dict:
    """Load generator: ``concurrency`` connections scoring the next text
    round-robin until ``requests`` total (default: one pass over
    ``texts``) have been answered. Returns client-observed stats:
    flows/s, p50/p95/p99 ms, reject count, per-reply batch sizes (the
    coalescing evidence tests assert on).

    ``pipeline`` > 1 keeps that many requests in flight PER CONNECTION
    (:class:`PipelinedScoringClient`) — the closed loop stops being
    bounded by one round-trip per connection. ``target_qps`` switches to
    open-loop pacing: requests are issued on a fixed fleet-wide schedule
    (request i not before ``t0 + i/target_qps``) regardless of how fast
    replies come back, which is how you measure a latency distribution
    AT a load point instead of the closed loop's self-throttled
    equilibrium; pacing implies pipelining (a paced sender must not
    block on the previous reply).

    ``arrival_trace`` replays a RECORDED inter-arrival pattern instead
    of a constant rate: gap ``j`` (seconds) separates request ``j`` from
    request ``j+1`` on the fleet-wide schedule, and the trace wraps
    whole-cycle when ``requests`` outruns it — a bursty recording stays
    bursty for the whole run. Open-loop like ``target_qps`` (the two are
    mutually exclusive), so the tail the service shows under real burst
    shapes is measurable, not the closed loop's smoothed-out version."""
    total = len(texts) if requests is None else int(requests)
    pipeline = max(1, int(pipeline))
    if target_qps is not None:
        if target_qps <= 0:
            raise ValueError(f"target_qps={target_qps} must be > 0")
        pipeline = max(pipeline, 32)  # pacing must not block on replies
    arrival_base: np.ndarray | None = None
    arrival_cycle = 0.0
    if arrival_trace is not None:
        if target_qps is not None:
            raise ValueError(
                "arrival_trace and target_qps are mutually exclusive "
                "(both fix the fleet-wide send schedule)"
            )
        gaps = np.asarray(list(arrival_trace), np.float64)
        if gaps.size == 0:
            raise ValueError("arrival_trace is empty")
        if (gaps < 0.0).any():
            raise ValueError("arrival_trace gaps must be >= 0")
        # Request j fires at the cumulative offset of the gaps BEFORE
        # it; past the recorded horizon the whole cycle repeats.
        arrival_base = np.concatenate(([0.0], np.cumsum(gaps[:-1])))
        arrival_cycle = float(gaps.sum())
        pipeline = max(pipeline, 32)  # pacing must not block on replies
    idx = iter(range(total))
    idx_lock = threading.Lock()
    latencies: list[float] = []
    batch_sizes: list[int] = []
    rejects = [0]
    errors: list[Exception] = []
    out_lock = threading.Lock()
    t_sched = time.monotonic()

    def worker_sync() -> None:
        with ScoringClient(
            host, port, timeout=timeout, auth_key=auth_key
        ) as cli:
            while True:
                with idx_lock:
                    i = next(idx, None)
                if i is None:
                    return
                t0 = time.monotonic()
                try:
                    reply = cli.score(
                        text=texts[i % len(texts)], deadline_ms=deadline_ms
                    )
                except ScoreRejected:
                    with out_lock:
                        rejects[0] += 1
                    continue
                dt = time.monotonic() - t0
                with out_lock:
                    latencies.append(dt)
                    batch_sizes.append(int(reply["batch_size"]))

    def worker_pipelined() -> None:
        import collections

        def on_done(fut, t0) -> None:
            # Runs on the reader thread AT resolution — the latency is
            # send -> reply, not send -> whenever-the-sender-drained.
            dt = time.monotonic() - t0
            try:
                reply = fut.result()
            except ScoreRejected:
                with out_lock:
                    rejects[0] += 1
                return
            except Exception:
                return  # surfaced by the drain's result() below
            with out_lock:
                latencies.append(dt)
                batch_sizes.append(int(reply["batch_size"]))

        def drain(fut) -> None:
            # Backpressure + error surfacing only; recording happened in
            # the done-callback.
            try:
                fut.result(timeout=timeout)
            except ScoreRejected:
                pass

        with PipelinedScoringClient(
            host, port, timeout=timeout, auth_key=auth_key
        ) as cli:
            window: collections.deque = collections.deque()
            while True:
                with idx_lock:
                    i = next(idx, None)
                if i is None:
                    break
                if target_qps is not None:
                    # Fleet-wide schedule: request i fires at i/qps.
                    delay = (t_sched + i / target_qps) - time.monotonic()
                    if delay > 0:
                        time.sleep(delay)
                elif arrival_base is not None:
                    # Recorded schedule: request i fires at its trace
                    # offset (whole cycles past the recorded horizon).
                    n_base = len(arrival_base)
                    offset = (
                        (i // n_base) * arrival_cycle
                        + arrival_base[i % n_base]
                    )
                    delay = (t_sched + offset) - time.monotonic()
                    if delay > 0:
                        time.sleep(delay)
                t0 = time.monotonic()
                fut = cli.submit(
                    text=texts[i % len(texts)], deadline_ms=deadline_ms
                )
                fut.add_done_callback(lambda f, t0=t0: on_done(f, t0))
                window.append(fut)
                while len(window) >= pipeline:
                    drain(window.popleft())
            while window:
                drain(window.popleft())

    def worker() -> None:
        try:
            if pipeline > 1:
                worker_pipelined()
            else:
                worker_sync()
        except Exception as e:  # surface worker crashes to the caller
            with out_lock:
                errors.append(e)

    threads = [
        threading.Thread(target=worker, daemon=True)
        for _ in range(max(1, concurrency))
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout + 30.0)
    wall = max(time.monotonic() - t0, 1e-9)
    if errors:
        raise errors[0]
    lat = np.asarray(latencies, np.float64) * 1e3
    pct = (
        {f"p{p}_ms": float(np.percentile(lat, p)) for p in (50, 95, 99)}
        if lat.size
        else {"p50_ms": 0.0, "p95_ms": 0.0, "p99_ms": 0.0}
    )
    return {
        "scored": len(latencies),
        "rejected": rejects[0],
        "wall_s": wall,
        "flows_per_sec": len(latencies) / wall,
        "target_qps": target_qps,
        "arrival_trace_len": (
            len(arrival_base) if arrival_base is not None else None
        ),
        "arrival_cycle_s": (
            arrival_cycle if arrival_base is not None else None
        ),
        "pipeline": pipeline,
        "mean_batch": float(np.mean(batch_sizes)) if batch_sizes else 0.0,
        "max_batch": max(batch_sizes, default=0),
        "batch_sizes": batch_sizes,
        **pct,
    }
