"""Scoring-service SDK + load generator (shared by tests and bench.py).

One :class:`ScoringClient` = one TCP connection with synchronous
request/reply (``score()``); concurrency comes from many clients — which
is exactly what makes the server's micro-batcher earn its keep: N
concurrent connections coalesce into one padded bucket dispatch.
:func:`run_load` spins that shape up (a thread per connection, a shared
work queue) and reports client-observed throughput and latency
percentiles — the numbers bench.py publishes.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any, Mapping, Sequence

import numpy as np

from ..comm import framing
from ..comm.wire import NONCE_LEN, NONCE_MAGIC, WireError
from . import protocol


class ScoreRejected(Exception):
    """Explicit server-side refusal (admission control / deadline)."""

    def __init__(self, code: int, reason: str, req_id: int):
        super().__init__(f"request {req_id} rejected ({code}): {reason}")
        self.code = int(code)
        self.reason = reason
        self.req_id = int(req_id)


class ScoringClient:
    """Blocking scoring connection. Not thread-safe; one per thread.

    ``auth_key``: the scoring port's shared secret (server ``--auth``):
    the constructor answers the server's per-connection nonce challenge
    before the first request. Against a server that requires auth, a
    keyless client fails with a clear WireError on its first score()
    (the challenge frame arrives where the reply was expected)."""

    def __init__(
        self,
        host: str,
        port: int,
        *,
        timeout: float = 30.0,
        auth_key: bytes | None = None,
    ):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.settimeout(timeout)
        self._next_id = 0
        if auth_key is not None:
            try:
                chal = bytes(framing.recv_frame(self.sock, send_ack=False))
            except (OSError, ConnectionError) as e:
                self.close()
                raise WireError(
                    "server sent no auth challenge — is it running with "
                    f"--auth? ({e})"
                ) from None
            if len(chal) != len(NONCE_MAGIC) + NONCE_LEN or not chal.startswith(
                NONCE_MAGIC
            ):
                self.close()
                raise WireError(
                    f"bad auth challenge from server (magic {chal[:4]!r})"
                )
            framing.send_frame(
                self.sock,
                protocol.build_auth_response(
                    auth_key, chal[len(NONCE_MAGIC) :]
                ),
                await_ack=False,
            )

    def score(
        self,
        *,
        text: str | None = None,
        features: Mapping[str, Any] | None = None,
        deadline_ms: float | None = None,
        trace: str | None = None,
    ) -> dict:
        """Score one flow; returns the reply dict (prob, prediction,
        round, batch_size, bucket, queue_ms — plus ``trace`` echoed when
        the request carried one). Raises :class:`ScoreRejected` on an
        explicit reject frame."""
        self._next_id += 1
        req_id = self._next_id
        framing.send_frame(
            self.sock,
            protocol.build_request(
                req_id,
                text=text,
                features=features,
                deadline_ms=deadline_ms,
                trace=trace,
            ),
            await_ack=False,
        )
        reply = bytes(framing.recv_frame(self.sock, send_ack=False))
        if reply[:4] == NONCE_MAGIC:
            # The server's auth challenge landed where the reply was
            # expected: this client connected without a key to an
            # --auth server. Name the fix instead of a generic magic error.
            raise WireError(
                "server requires authentication — construct the client "
                "with auth_key (server runs with --auth)"
            )
        if protocol.is_reject(reply):
            body = protocol.parse_reject(reply)
            raise ScoreRejected(body["code"], body["reason"], body["id"])
        body = protocol.parse_reply(reply)
        if body["id"] != req_id:
            raise WireError(
                f"reply for request {body['id']} arrived while awaiting "
                f"{req_id} (synchronous client; server must answer in order)"
            )
        return body

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def __enter__(self) -> "ScoringClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def run_load(
    host: str,
    port: int,
    texts: Sequence[str],
    *,
    concurrency: int = 4,
    requests: int | None = None,
    deadline_ms: float | None = None,
    timeout: float = 60.0,
    auth_key: bytes | None = None,
) -> dict:
    """Closed-loop load generator: ``concurrency`` connections, each
    scoring the next text round-robin until ``requests`` total (default:
    one pass over ``texts``) have been answered. Returns client-observed
    stats: flows/s, p50/p95/p99 ms, reject count, per-reply batch sizes
    (the coalescing evidence tests assert on)."""
    total = len(texts) if requests is None else int(requests)
    idx = iter(range(total))
    idx_lock = threading.Lock()
    latencies: list[float] = []
    batch_sizes: list[int] = []
    rejects = [0]
    errors: list[Exception] = []
    out_lock = threading.Lock()

    def worker() -> None:
        try:
            with ScoringClient(
                host, port, timeout=timeout, auth_key=auth_key
            ) as cli:
                while True:
                    with idx_lock:
                        i = next(idx, None)
                    if i is None:
                        return
                    t0 = time.monotonic()
                    try:
                        reply = cli.score(
                            text=texts[i % len(texts)],
                            deadline_ms=deadline_ms,
                        )
                    except ScoreRejected:
                        with out_lock:
                            rejects[0] += 1
                        continue
                    dt = time.monotonic() - t0
                    with out_lock:
                        latencies.append(dt)
                        batch_sizes.append(int(reply["batch_size"]))
        except Exception as e:  # surface worker crashes to the caller
            with out_lock:
                errors.append(e)

    threads = [
        threading.Thread(target=worker, daemon=True)
        for _ in range(max(1, concurrency))
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout + 30.0)
    wall = max(time.monotonic() - t0, 1e-9)
    if errors:
        raise errors[0]
    lat = np.asarray(latencies, np.float64) * 1e3
    pct = (
        {f"p{p}_ms": float(np.percentile(lat, p)) for p in (50, 95, 99)}
        if lat.size
        else {"p50_ms": 0.0, "p95_ms": 0.0, "p99_ms": 0.0}
    )
    return {
        "scored": len(latencies),
        "rejected": rejects[0],
        "wall_s": wall,
        "flows_per_sec": len(latencies) / wall,
        "mean_batch": float(np.mean(batch_sizes)) if batch_sizes else 0.0,
        "max_batch": max(batch_sizes, default=0),
        "batch_sizes": batch_sizes,
        **pct,
    }
