"""Scoring request/reply/reject codecs over the length-framed wire.

One frame = 4-byte magic (comm/wire.py ``SCORE_*``) + a UTF-8 JSON body.
JSON, not the tensor manifest: a scoring exchange moves one flow record
and a handful of floats, so the non-executable-payload argument that
shaped comm/wire.py holds trivially — ``json.loads`` cannot encode code —
and the frames stay greppable on the wire.

Float exactness: ``prob`` crosses as a JSON double. float32 -> float64 is
exact and Python's repr round-trips doubles exactly, so a reply compares
bit-for-bit against the float32 probability ``fedtpu predict`` computes
(``float(np.float32(p)) == reply["prob"]``) — pinned by the e2e test.

Frames ride :func:`comm.framing.send_frame` with ``await_ack=False`` in
BOTH directions (see that module): the reply is the acknowledgment, and
keeping ACK bytes off the socket means the scorer thread's reply writes
can never interleave with the reader thread's ACKs.
"""

from __future__ import annotations

import hmac
import json
import re
from typing import Any, Mapping

from ..comm.wire import (
    SCORE_AUTH_DOMAIN,
    SCORE_AUTH_MAGIC,
    SCORE_REJ_MAGIC,
    SCORE_RELOAD_MAGIC,
    SCORE_RELOADR_MAGIC,
    SCORE_REP_MAGIC,
    SCORE_REQ_MAGIC,
    SCORE_STAT_MAGIC,
    SCORE_STATR_MAGIC,
    WireError,
)

#: Reject codes (HTTP-flavored for operator familiarity): the service is
#: over capacity (queue full at admission) or the request sat past its
#: deadline before a scorer slot opened.
REJECT_OVERLOADED = 503
REJECT_DEADLINE = 504


def _build(magic: bytes, body: Mapping[str, Any]) -> bytes:
    return magic + json.dumps(body, separators=(",", ":")).encode()


def _parse(frame: bytes, magic: bytes, kind: str) -> dict:
    frame = bytes(frame)
    if frame[:4] != magic:
        raise WireError(
            f"not a scoring {kind} frame (magic {frame[:4]!r})"
        )
    try:
        body = json.loads(frame[4:].decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"malformed scoring {kind} body: {e}") from None
    if not isinstance(body, dict):
        raise WireError(f"scoring {kind} body must be a JSON object")
    return body


# ----------------------------------------------------------------- request
def build_request(
    req_id: int,
    *,
    text: str | None = None,
    features: Mapping[str, Any] | None = None,
    deadline_ms: float | None = None,
    trace: str | None = None,
) -> bytes:
    """One flow record to score: either the rendered template ``text`` or
    the raw ``features`` mapping (rendered server-side through the active
    dataset's template — the same bytes ``predict`` would feed). Exactly
    one of the two. ``deadline_ms`` is this request's latency budget;
    past it the server answers with an explicit reject, never a hang.
    ``trace`` is the optional obs trace id (obs/trace.py) echoed in the
    reply — a caller's distributed-tracing hook; old peers that omit it
    (or servers that ignore it) interop unchanged."""
    if (text is None) == (features is None):
        raise ValueError("pass exactly one of text= or features=")
    body: dict[str, Any] = {"id": int(req_id)}
    if text is not None:
        body["text"] = str(text)
    else:
        body["features"] = dict(features)
    if deadline_ms is not None:
        body["deadline_ms"] = float(deadline_ms)
    if trace is not None:
        body["trace"] = str(trace)
    return _build(SCORE_REQ_MAGIC, body)


def parse_request(frame: bytes) -> dict:
    """Validate types as well as presence: every field here is attacker-
    controlled network input, and a wrong-typed value must surface as a
    WireError (clean connection drop) — never as a TypeError escaping a
    reader thread."""
    body = _parse(frame, SCORE_REQ_MAGIC, "request")
    if not isinstance(body.get("id"), int) or isinstance(body["id"], bool):
        raise WireError("scoring request id must be an integer")
    if ("text" in body) == ("features" in body):
        raise WireError(
            "scoring request must carry exactly one of text/features"
        )
    if "text" in body and not isinstance(body["text"], str):
        raise WireError("scoring request text must be a string")
    if "features" in body and not isinstance(body["features"], dict):
        raise WireError("scoring request features must be an object")
    if "deadline_ms" in body and (
        not isinstance(body["deadline_ms"], (int, float))
        or isinstance(body["deadline_ms"], bool)
    ):
        raise WireError("scoring request deadline_ms must be a number")
    if "trace" in body and not isinstance(body["trace"], str):
        raise WireError("scoring request trace must be a string")
    return body


# ------------------------------------------------------------------- reply
def build_reply(
    req_id: int,
    *,
    prob: float,
    threshold: float,
    round_id: int,
    batch_size: int,
    bucket: int,
    queue_ms: float,
    trace: str | None = None,
    class_probs: list | None = None,
) -> bytes:
    """P(attack) + the per-request telemetry that makes the service
    observable from the client side alone: which model round answered,
    how large the coalesced batch was, and how long the request queued.
    ``trace`` echoes the request's obs trace id when it carried one.

    ``class_probs`` puts the per-class softmax on the wire (K-class
    heads) as an OPTIONAL key after the pinned leading fields: old SDKs
    keep reading the scalar ``prob`` (P(attack) = 1 - P(class 0) for
    K > 2, the eval path's score) and never see the new key; K-aware
    SDKs read the full distribution. Omitted when None, so a binary
    deployment's replies are byte-identical to the pre-K-class wire."""
    body = {
        "id": int(req_id),
        "prob": float(prob),
        "prediction": int(float(prob) >= threshold),
        "round": int(round_id),
        "batch_size": int(batch_size),
        "bucket": int(bucket),
        "queue_ms": round(float(queue_ms), 3),
    }
    if class_probs is not None:
        body["class_probs"] = [float(p) for p in class_probs]
    if trace is not None:
        body["trace"] = str(trace)
    return _build(SCORE_REP_MAGIC, body)


def parse_reply(frame: bytes) -> dict:
    body = _parse(frame, SCORE_REP_MAGIC, "reply")
    for key in ("id", "prob", "prediction", "round", "batch_size"):
        if key not in body:
            raise WireError(f"scoring reply missing {key!r}")
    return body


# ------------------------------------------------------------------ reject
def build_reject(req_id: int, *, code: int, reason: str) -> bytes:
    return _build(
        SCORE_REJ_MAGIC,
        {"id": int(req_id), "code": int(code), "reason": str(reason)},
    )


def parse_reject(frame: bytes) -> dict:
    body = _parse(frame, SCORE_REJ_MAGIC, "reject")
    for key in ("id", "code", "reason"):
        if key not in body:
            raise WireError(f"scoring reject missing {key!r}")
    return body


def is_reject(frame: bytes) -> bool:
    return bytes(frame[:4]) == SCORE_REJ_MAGIC


# ------------------------------------------------------------------- stats
def build_stats_request(req_id: int) -> bytes:
    """In-band telemetry probe: ask the server for its ``stats()``
    snapshot on this connection. Rides the ordinary request stream (same
    socket, same auth), which is what makes it the router's health probe:
    a replica that answers probes is a replica that answers requests."""
    return _build(SCORE_STAT_MAGIC, {"id": int(req_id)})


def parse_stats_request(frame: bytes) -> dict:
    body = _parse(frame, SCORE_STAT_MAGIC, "stats request")
    if not isinstance(body.get("id"), int) or isinstance(body["id"], bool):
        raise WireError("stats request id must be an integer")
    return body


def is_stats_request(frame: bytes) -> bool:
    return bytes(frame[:4]) == SCORE_STAT_MAGIC


def is_request(frame: bytes) -> bool:
    """Magic sniff only — the router's hot path routes on this plus
    :func:`frame_id`, leaving full body validation to the replica (which
    answers a malformed body with a 400 reject, so a hostile client
    cannot poison the shared router->replica connection)."""
    return bytes(frame[:4]) == SCORE_REQ_MAGIC


def build_stats_reply(req_id: int, stats: Mapping[str, Any]) -> bytes:
    return _build(
        SCORE_STATR_MAGIC, {"id": int(req_id), "stats": dict(stats)}
    )


def parse_stats_reply(frame: bytes) -> dict:
    body = _parse(frame, SCORE_STATR_MAGIC, "stats reply")
    if not isinstance(body.get("id"), int) or isinstance(body["id"], bool):
        raise WireError("stats reply id must be an integer")
    if not isinstance(body.get("stats"), dict):
        raise WireError("stats reply must carry a stats object")
    return body


def is_stats_reply(frame: bytes) -> bool:
    return bytes(frame[:4]) == SCORE_STATR_MAGIC


# ------------------------------------------------------------------ reload
def build_reload_request(req_id: int) -> bytes:
    """Drain-then-reload-now control frame (comm/wire.py SCORE_RELOAD):
    ask the replica to check its checkpoint/registry watcher IMMEDIATELY
    (bypassing the poll interval) at the next batch boundary, and answer
    only once the adoption attempt finished. The out-of-process rolling
    reload's coordination primitive: the router drains a replica, sends
    this on the same authenticated backend connection, and readmits on
    the reply."""
    return _build(SCORE_RELOAD_MAGIC, {"id": int(req_id)})


def parse_reload_request(frame: bytes) -> dict:
    body = _parse(frame, SCORE_RELOAD_MAGIC, "reload request")
    if not isinstance(body.get("id"), int) or isinstance(body["id"], bool):
        raise WireError("reload request id must be an integer")
    return body


def is_reload_request(frame: bytes) -> bool:
    return bytes(frame[:4]) == SCORE_RELOAD_MAGIC


def build_reload_reply(
    req_id: int, *, reloaded: bool, round_id: int
) -> bytes:
    """``reloaded`` = whether the forced watcher poll adopted anything;
    ``round`` = the model round serving AFTER the attempt (the manager's
    completion check)."""
    return _build(
        SCORE_RELOADR_MAGIC,
        {
            "id": int(req_id),
            "reloaded": bool(reloaded),
            "round": int(round_id),
        },
    )


def parse_reload_reply(frame: bytes) -> dict:
    body = _parse(frame, SCORE_RELOADR_MAGIC, "reload reply")
    for key in ("id", "reloaded", "round"):
        if key not in body:
            raise WireError(f"reload reply missing {key!r}")
    if not isinstance(body["id"], int) or isinstance(body["id"], bool):
        raise WireError("reload reply id must be an integer")
    return body


def is_reload_reply(frame: bytes) -> bool:
    return bytes(frame[:4]) == SCORE_RELOADR_MAGIC


# ---------------------------------------------------------------- id remap
#: Frame types whose JSON body carries the correlating ``id`` field —
#: everything the router forwards or answers.
_ID_MAGICS = (
    SCORE_REQ_MAGIC,
    SCORE_REP_MAGIC,
    SCORE_REJ_MAGIC,
    SCORE_STAT_MAGIC,
    SCORE_STATR_MAGIC,
    SCORE_RELOAD_MAGIC,
    SCORE_RELOADR_MAGIC,
)

#: The canonical leading-``id`` shape every builder in this module
#: emits: ``MAGIC{"id":<int>,...`` — the id remap's fast path matches it
#: at the fixed position (anchored right after the magic), so the
#: router's hot path is a byte splice, not a parse+re-encode of the
#: whole body. A frame whose id is NOT at the canonical position (a
#: foreign builder, hostile input) falls back to the full JSON parse —
#: same result, just slower; correctness never rides the fast path.
_LEAD_ID_RE = re.compile(rb'^\{"id":(-?\d+)')


def frame_id(frame: bytes) -> int:
    """The correlating request id of any scoring frame (request, reply,
    reject, stats) without full per-type validation — what the router's
    reply path matches pending requests on."""
    magic = bytes(frame[:4])
    if magic not in _ID_MAGICS:
        raise WireError(f"not an id-correlated scoring frame ({magic!r})")
    window = bytes(frame[4:40])
    m = _LEAD_ID_RE.match(window)
    if m and m.end(1) < len(window):  # digit run terminated in-window
        return int(m.group(1))
    body = _parse(frame, magic, "scoring")
    rid = body.get("id")
    if not isinstance(rid, int) or isinstance(rid, bool):
        raise WireError("scoring frame id must be an integer")
    return rid


def rewrite_id(frame: bytes, new_id: int) -> bytes:
    """Re-address a scoring frame to a different request id (the body is
    otherwise untouched). The router multiplexes many client connections
    onto one backend connection, so client-chosen ids collide — each
    forwarded request gets a router-minted id, and the matching reply is
    rewritten back. The fast path splices the canonical leading id in
    place — every other body byte is preserved EXACTLY, so a rewritten
    reply's ``prob`` is bit-identical to the replica's original; the
    JSON fallback preserves it too (doubles round-trip bit-for-bit
    through ``json.loads``/``dumps``)."""
    frame = bytes(frame)
    magic = frame[:4]
    if magic not in _ID_MAGICS:
        raise WireError(f"not an id-correlated scoring frame ({magic!r})")
    window = frame[4:40]
    m = _LEAD_ID_RE.match(window)
    if m and m.end(1) < len(window):  # digit run terminated in-window
        return (
            frame[:4]
            + b'{"id":'
            + str(int(new_id)).encode()
            + frame[4 + m.end(1) :]
        )
    body = _parse(frame, magic, "scoring")
    body["id"] = int(new_id)
    return _build(magic, body)


# -------------------------------------------------------------------- auth
def build_auth_response(auth_key: bytes, nonce: bytes) -> bytes:
    """The client's proof for the server's per-connection nonce challenge
    (the FL tier's challenge-response reused on the scoring port):
    ``SCORE_AUTH_MAGIC + HMAC-SHA256(key, domain + nonce)``. Domain
    separation keeps the proof from doubling as any FL-tier tag."""
    return SCORE_AUTH_MAGIC + hmac.new(
        auth_key, SCORE_AUTH_DOMAIN + bytes(nonce), "sha256"
    ).digest()


def check_auth_response(frame: bytes, auth_key: bytes, nonce: bytes) -> bool:
    """Constant-time verification of a client's auth proof."""
    want = build_auth_response(auth_key, nonce)
    return hmac.compare_digest(bytes(frame), want)
