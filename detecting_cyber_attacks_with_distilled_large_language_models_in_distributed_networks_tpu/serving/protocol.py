"""Scoring request/reply/reject codecs over the length-framed wire.

One frame = 4-byte magic (comm/wire.py ``SCORE_*``) + a UTF-8 JSON body.
JSON, not the tensor manifest: a scoring exchange moves one flow record
and a handful of floats, so the non-executable-payload argument that
shaped comm/wire.py holds trivially — ``json.loads`` cannot encode code —
and the frames stay greppable on the wire.

Float exactness: ``prob`` crosses as a JSON double. float32 -> float64 is
exact and Python's repr round-trips doubles exactly, so a reply compares
bit-for-bit against the float32 probability ``fedtpu predict`` computes
(``float(np.float32(p)) == reply["prob"]``) — pinned by the e2e test.

Frames ride :func:`comm.framing.send_frame` with ``await_ack=False`` in
BOTH directions (see that module): the reply is the acknowledgment, and
keeping ACK bytes off the socket means the scorer thread's reply writes
can never interleave with the reader thread's ACKs.
"""

from __future__ import annotations

import hmac
import json
from typing import Any, Mapping

from ..comm.wire import (
    SCORE_AUTH_DOMAIN,
    SCORE_AUTH_MAGIC,
    SCORE_REJ_MAGIC,
    SCORE_REP_MAGIC,
    SCORE_REQ_MAGIC,
    WireError,
)

#: Reject codes (HTTP-flavored for operator familiarity): the service is
#: over capacity (queue full at admission) or the request sat past its
#: deadline before a scorer slot opened.
REJECT_OVERLOADED = 503
REJECT_DEADLINE = 504


def _build(magic: bytes, body: Mapping[str, Any]) -> bytes:
    return magic + json.dumps(body, separators=(",", ":")).encode()


def _parse(frame: bytes, magic: bytes, kind: str) -> dict:
    frame = bytes(frame)
    if frame[:4] != magic:
        raise WireError(
            f"not a scoring {kind} frame (magic {frame[:4]!r})"
        )
    try:
        body = json.loads(frame[4:].decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"malformed scoring {kind} body: {e}") from None
    if not isinstance(body, dict):
        raise WireError(f"scoring {kind} body must be a JSON object")
    return body


# ----------------------------------------------------------------- request
def build_request(
    req_id: int,
    *,
    text: str | None = None,
    features: Mapping[str, Any] | None = None,
    deadline_ms: float | None = None,
    trace: str | None = None,
) -> bytes:
    """One flow record to score: either the rendered template ``text`` or
    the raw ``features`` mapping (rendered server-side through the active
    dataset's template — the same bytes ``predict`` would feed). Exactly
    one of the two. ``deadline_ms`` is this request's latency budget;
    past it the server answers with an explicit reject, never a hang.
    ``trace`` is the optional obs trace id (obs/trace.py) echoed in the
    reply — a caller's distributed-tracing hook; old peers that omit it
    (or servers that ignore it) interop unchanged."""
    if (text is None) == (features is None):
        raise ValueError("pass exactly one of text= or features=")
    body: dict[str, Any] = {"id": int(req_id)}
    if text is not None:
        body["text"] = str(text)
    else:
        body["features"] = dict(features)
    if deadline_ms is not None:
        body["deadline_ms"] = float(deadline_ms)
    if trace is not None:
        body["trace"] = str(trace)
    return _build(SCORE_REQ_MAGIC, body)


def parse_request(frame: bytes) -> dict:
    """Validate types as well as presence: every field here is attacker-
    controlled network input, and a wrong-typed value must surface as a
    WireError (clean connection drop) — never as a TypeError escaping a
    reader thread."""
    body = _parse(frame, SCORE_REQ_MAGIC, "request")
    if not isinstance(body.get("id"), int) or isinstance(body["id"], bool):
        raise WireError("scoring request id must be an integer")
    if ("text" in body) == ("features" in body):
        raise WireError(
            "scoring request must carry exactly one of text/features"
        )
    if "text" in body and not isinstance(body["text"], str):
        raise WireError("scoring request text must be a string")
    if "features" in body and not isinstance(body["features"], dict):
        raise WireError("scoring request features must be an object")
    if "deadline_ms" in body and (
        not isinstance(body["deadline_ms"], (int, float))
        or isinstance(body["deadline_ms"], bool)
    ):
        raise WireError("scoring request deadline_ms must be a number")
    if "trace" in body and not isinstance(body["trace"], str):
        raise WireError("scoring request trace must be a string")
    return body


# ------------------------------------------------------------------- reply
def build_reply(
    req_id: int,
    *,
    prob: float,
    threshold: float,
    round_id: int,
    batch_size: int,
    bucket: int,
    queue_ms: float,
    trace: str | None = None,
) -> bytes:
    """P(attack) + the per-request telemetry that makes the service
    observable from the client side alone: which model round answered,
    how large the coalesced batch was, and how long the request queued.
    ``trace`` echoes the request's obs trace id when it carried one."""
    body = {
        "id": int(req_id),
        "prob": float(prob),
        "prediction": int(float(prob) >= threshold),
        "round": int(round_id),
        "batch_size": int(batch_size),
        "bucket": int(bucket),
        "queue_ms": round(float(queue_ms), 3),
    }
    if trace is not None:
        body["trace"] = str(trace)
    return _build(SCORE_REP_MAGIC, body)


def parse_reply(frame: bytes) -> dict:
    body = _parse(frame, SCORE_REP_MAGIC, "reply")
    for key in ("id", "prob", "prediction", "round", "batch_size"):
        if key not in body:
            raise WireError(f"scoring reply missing {key!r}")
    return body


# ------------------------------------------------------------------ reject
def build_reject(req_id: int, *, code: int, reason: str) -> bytes:
    return _build(
        SCORE_REJ_MAGIC,
        {"id": int(req_id), "code": int(code), "reason": str(reason)},
    )


def parse_reject(frame: bytes) -> dict:
    body = _parse(frame, SCORE_REJ_MAGIC, "reject")
    for key in ("id", "code", "reason"):
        if key not in body:
            raise WireError(f"scoring reject missing {key!r}")
    return body


def is_reject(frame: bytes) -> bool:
    return bytes(frame[:4]) == SCORE_REJ_MAGIC


# -------------------------------------------------------------------- auth
def build_auth_response(auth_key: bytes, nonce: bytes) -> bytes:
    """The client's proof for the server's per-connection nonce challenge
    (the FL tier's challenge-response reused on the scoring port):
    ``SCORE_AUTH_MAGIC + HMAC-SHA256(key, domain + nonce)``. Domain
    separation keeps the proof from doubling as any FL-tier tag."""
    return SCORE_AUTH_MAGIC + hmac.new(
        auth_key, SCORE_AUTH_DOMAIN + bytes(nonce), "sha256"
    ).digest()


def check_auth_response(frame: bytes, auth_key: bytes, nonce: bytes) -> bool:
    """Constant-time verification of a client's auth proof."""
    want = build_auth_response(auth_key, nonce)
    return hmac.compare_digest(bytes(frame), want)
