"""The scoring service: accept loop, scorer thread, telemetry.

Thread layout (fixed, small, lock-light):

* one **accept** thread hands each connection to a reader thread;
* one **reader** thread per connection parses + tokenizes requests (the
  WordPiece work rides the connection thread, in parallel across
  clients, keeping the scorer hot path pure) and submits them to the
  micro-batcher — a full queue is answered with the explicit reject
  frame right there;
* one **scorer** thread owns the JAX dispatch: coalesce, drop expired
  requests with deadline rejects, score the rest through the bucketed
  engine, write replies. Its idle tick polls the checkpoint watcher, so
  reloads never race a batch.

Per-connection writes (replies, rejects) go through a bounded outbound
queue drained by a per-connection **writer** thread — the scorer thread
never touches a socket, so a non-reading client (full TCP buffers,
blocking sendall) stalls only its own writer, never the service; when a
connection's outbound queue fills, that connection is dropped. No ACK
bytes ride the scoring sockets (framing ``await_ack=False`` both
directions), so reader and writer writes cannot interleave.

Telemetry: every reply carries (model round, batch size, queue wait);
the server accumulates latency percentiles (p50/p95/p99), throughput,
and reject counts — surfaced via ``stats()``, appended per-batch to the
metrics-JSONL channel when configured, and summarized on close.
"""

from __future__ import annotations

import collections
import socket
import threading
import time
from typing import Any

import numpy as np

from ..comm import framing
from ..comm.wire import WireError
from ..data.textualize import render_row
from ..obs import flight as obs_flight
from ..obs import metrics as obs_metrics
from ..utils.logging import get_logger
from . import protocol
from .batcher import MicroBatcher, ScoreRequest

log = get_logger()

#: A scoring request is one flow record — bound the frame allocation far
#: below the transport's model-sized MAX_FRAME.
MAX_REQUEST_FRAME = 1 << 20  # 1 MB


class _ConnWriter:
    """Per-connection outbound lane: a bounded queue + one writer thread.

    The scorer thread calls :meth:`send` (non-blocking put); only this
    writer ever does the blocking ``sendall``, so one non-reading client
    can never head-of-line-block scoring for everyone else. A full queue
    means the peer has stopped draining replies — the connection is
    closed (its un-read replies were lost to it anyway)."""

    def __init__(self, conn: socket.socket, *, maxsize: int = 256):
        import queue

        self._conn = conn
        self._q: "queue.Queue[bytes | None]" = queue.Queue(maxsize=maxsize)
        self._dead = threading.Event()
        self._t = threading.Thread(target=self._drain, daemon=True)
        self._t.start()

    def send(self, frame: bytes) -> None:
        import queue

        if self._dead.is_set():
            return
        try:
            self._q.put_nowait(frame)
        except queue.Full:
            self.kill()

    def _drain(self) -> None:
        while True:
            frame = self._q.get()
            if frame is None or self._dead.is_set():
                return
            try:
                framing.send_frame(self._conn, frame, await_ack=False)
            except OSError:
                self.kill()
                return

    def kill(self) -> None:
        """Tear the connection down (peer gone or not draining)."""
        self._dead.set()
        try:
            self._conn.close()  # also unblocks the reader thread
        except OSError:
            pass
        try:
            self._q.put_nowait(None)
        except Exception:
            pass

    def close(self) -> None:
        """Stop the writer after the queue drains (normal teardown)."""
        try:
            self._q.put(None, timeout=1.0)
        except Exception:
            self._dead.set()
        self._t.join(timeout=5.0)


class ScoringServer:
    """TCP scoring service over a :class:`~.engine.ScoreEngine`.

    ``spec`` (a data.datasets.DatasetSpec) renders ``features`` requests
    through the active dataset's template — the same bytes ``predict``
    feeds; ``text`` requests skip rendering. ``default_deadline_s``
    applies to requests that name no budget (None = wait forever).
    """

    def __init__(
        self,
        engine,
        tokenizer,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        spec=None,
        threshold: float = 0.5,
        batcher: MicroBatcher | None = None,
        watcher=None,
        default_deadline_s: float | None = None,
        idle_tick_s: float = 0.05,
        metrics_jsonl: str | None = None,
        scored_jsonl: str | None = None,
        warmup: bool = True,
        latency_window: int = 100_000,
        auth_key: bytes | None = None,
        score_bins: int = 10,
        tracer=None,
        trace_sample: float = 1.0,
        replica_id: int | None = None,
    ):
        if not 0.0 < float(trace_sample) <= 1.0:
            raise ValueError(
                f"trace_sample={trace_sample} must be in (0, 1]"
            )
        # serve-batch span sampling (ObsConfig.trace_sample / the
        # --trace-sample flag): one span per ``stride`` coalesced batches
        # via the batch COUNTER — deterministic (reruns sample the same
        # batches, no RNG in the hot path), and the events-JSONL stops
        # growing one line per batch on a high-rate scorer. Each emitted
        # span carries ``sampled_batches`` so consumers re-scale.
        self._trace_stride = max(1, round(1.0 / float(trace_sample)))
        self.engine = engine
        self.tok = tokenizer
        self.spec = spec
        self.threshold = float(threshold)
        self.batcher = batcher or MicroBatcher(max_batch=engine.buckets[-1])
        if self.batcher.max_batch > engine.buckets[-1]:
            raise ValueError(
                f"batcher.max_batch={self.batcher.max_batch} exceeds the "
                f"largest engine bucket {engine.buckets[-1]}"
            )
        self.watcher = watcher
        # Fleet identity (router/): stamped into stats() so a probe can
        # tell WHICH replica answered; None = standalone deployment.
        self.replica_id = None if replica_id is None else int(replica_id)
        self.default_deadline_s = default_deadline_s
        self.idle_tick_s = float(idle_tick_s)
        self.metrics_jsonl = metrics_jsonl
        # Opt-in scored-record export (labels/join.py's serving-side
        # stream): one line per ANSWERED request carrying the request id
        # and the raw probability — the join key against the delayed
        # ground-truth journal. Off by default: the metrics stream's
        # "binned counts, never raw scores" contract is unchanged; this
        # channel exists precisely because supervised evaluation needs
        # the per-request answer.
        self.scored_jsonl = scored_jsonl
        self._warmup = warmup
        self._closed = threading.Event()
        self._threads: list[threading.Thread] = []
        self._conns: set[socket.socket] = set()
        self._conn_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self._scored = 0
        self._batches = 0
        self._rejects = {
            "deadline": 0, "overloaded": 0, "bad_request": 0, "error": 0,
            "auth": 0,
        }
        # Out-of-process reload choreography (comm/wire.py SCORE_RELOAD):
        # reader threads enqueue (req_id, writer) here; the scorer thread
        # answers at its next batch boundary after a FORCED watcher poll,
        # so the reply means "the adoption attempt finished", not "the
        # frame arrived". _reload_frames counts arrivals for stats() (the
        # in-process rolling-reload regression asserts it stays 0).
        self._reload_q: collections.deque = collections.deque()
        self._reload_frames = 0
        # Scoring-port auth (the FL tier's HMAC challenge-response reused
        # here): with a key, every connection must answer the nonce
        # challenge before its first request is read. None = the
        # reference-style open port, exactly as before.
        self.auth_key = auth_key
        # Score-distribution export for the drift monitor
        # (control/drift.py): per-batch probability histograms over fixed
        # [0, 1] bins — the SAME binning train/fedeval.reference_histogram
        # uses for the promoted artifact's eval fingerprint.
        self._hist_edges = np.linspace(0.0, 1.0, int(score_bins) + 1)
        self._score_hist = np.zeros(int(score_bins), np.int64)
        self._batch_hist: collections.Counter[int] = collections.Counter()
        self._latencies: collections.deque[float] = collections.deque(
            maxlen=latency_window
        )
        # Observability (obs/): optional serve-batch span tracer + the
        # process gauge registry the /metrics endpoint renders. Queue
        # depth and reject counts existed internally but never reached
        # the exported surfaces; both land in stats(), the per-batch
        # JSONL record, and the gauge registry now.
        self.tracer = tracer
        m = obs_metrics.default_registry()
        self._g_queue = m.gauge(
            "fedtpu_serve_queue_depth",
            help="scoring requests waiting in the micro-batcher",
        )
        self._g_round = m.gauge(
            "fedtpu_serve_model_round",
            help="model round currently serving",
        )
        self._m_scored = m.counter(
            "fedtpu_serve_scored_total", help="flows scored"
        )
        self._m_batches = m.counter(
            "fedtpu_serve_batches_total", help="coalesced score dispatches"
        )
        self._m_rejects = {
            kind: m.counter(
                "fedtpu_serve_rejects_total",
                help="explicit reject frames by kind",
                labels={"kind": kind},
            )
            for kind in self._rejects
        }
        self._h_queue_ms = m.histogram(
            "fedtpu_serve_queue_wait_seconds",
            help="request queue wait before dispatch",
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.5, 1.0, 5.0),
        )
        self._t_start = time.monotonic()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.port = self._sock.getsockname()[1]

    # ---------------------------------------------------------------- control
    def start(self) -> "ScoringServer":
        # Prime BEFORE the (multi-second) warmup, and only when the
        # caller didn't already prime with the step it restored: a
        # checkpoint finalized during warmup must count as new, not be
        # silently marked seen-but-never-loaded.
        if self.watcher is not None and not self.watcher.primed:
            self.watcher.prime()
        if self._warmup:
            self.engine.warmup()
        self._sock.listen(64)
        for target, name in (
            (self._accept_loop, "accept"),
            (self._score_loop, "scorer"),
        ):
            t = threading.Thread(
                target=target, name=f"fedtpu-serve-{name}", daemon=True
            )
            t.start()
            self._threads.append(t)
        log.info(
            f"[SERVE] scoring service on port {self.port} (buckets "
            f"{self.engine.buckets}, seq {self.engine.seq_len}, window "
            f"{self.batcher.gather_window_s * 1e3:.1f} ms, queue cap "
            f"{self.batcher.max_queue})"
        )
        return self

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conn_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5.0)
        s = self.stats()
        log.info(
            f"[SERVE] served {s['scored']} flows in {s['uptime_s']:.1f}s "
            f"({s['flows_per_sec']:.1f} flows/s), p50 {s['p50_ms']:.2f} ms "
            f"p95 {s['p95_ms']:.2f} ms p99 {s['p99_ms']:.2f} ms, rejects "
            f"{s['rejects']}"
        )
        if self.metrics_jsonl:
            from ..reporting import append_metrics_jsonl

            append_metrics_jsonl(
                self.metrics_jsonl, {"phase": "serve_summary", **_flat(s)}
            )

    def __enter__(self) -> "ScoringServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # ----------------------------------------------------------------- stats
    def stats(self) -> dict:
        with self._stats_lock:
            lat = np.asarray(self._latencies, np.float64) * 1e3
            scored = self._scored
            batches = self._batches
            rejects = dict(self._rejects)
            hist = dict(sorted(self._batch_hist.items()))
            score_hist = self._score_hist.tolist()
            reload_frames = self._reload_frames
        uptime = max(time.monotonic() - self._t_start, 1e-9)
        pct = (
            {
                f"p{p}_ms": float(np.percentile(lat, p))
                for p in (50, 95, 99)
            }
            if lat.size
            else {"p50_ms": 0.0, "p95_ms": 0.0, "p99_ms": 0.0}
        )
        return {
            "replica": self.replica_id,
            "scored": scored,
            "batches": batches,
            "mean_batch": scored / batches if batches else 0.0,
            "batch_size_hist": hist,
            "score_hist": score_hist,
            "rejects": rejects,
            "rejects_total": sum(rejects.values()),
            "queue_depth": self.batcher.qsize(),
            "reloads": getattr(self.watcher, "reload_count", 0),
            "reload_frames": reload_frames,
            "round": self.engine.round_id,
            "uptime_s": uptime,
            "flows_per_sec": scored / uptime,
            **pct,
        }

    # ----------------------------------------------------------- accept path
    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return  # listener closed
            try:
                # Scoring frames are small and the transport writes
                # header + payload separately (write-write-read): Nagle
                # + delayed ACK turns that into per-frame stalls under
                # multi-hop (router) deployments. Latency beats batching
                # bytes here.
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            with self._conn_lock:
                self._conns.add(conn)
            t = threading.Thread(
                target=self._reader_loop, args=(conn,), daemon=True
            )
            t.start()

    def _reader_loop(self, conn: socket.socket) -> None:
        if self.auth_key is not None and not self._auth_handshake(conn):
            with self._conn_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass
            return
        writer = _ConnWriter(conn)
        seq_len = self.engine.seq_len
        try:
            while not self._closed.is_set():
                try:
                    frame = framing.recv_frame(
                        conn, send_ack=False, max_frame=MAX_REQUEST_FRAME
                    )
                except (ConnectionError, OSError):
                    return
                except WireError as e:
                    # Oversized/corrupt frame: the stream is desynced —
                    # drop the connection (cleanly; no thread excepthook
                    # noise), the client sees EOF and reconnects.
                    log.warning(f"[SERVE] dropping connection: {e}")
                    return
                fb = bytes(frame)
                if protocol.is_stats_request(fb):
                    # In-band telemetry probe (router health checks, ops
                    # tooling): answered from the reader thread — a probe
                    # must not queue behind scoring work, its whole point
                    # is to answer while the scorer is busy.
                    try:
                        sbody = protocol.parse_stats_request(fb)
                    except WireError as e:
                        log.warning(f"[SERVE] dropping connection: {e}")
                        return
                    writer.send(
                        protocol.build_stats_reply(sbody["id"], self.stats())
                    )
                    continue
                if protocol.is_reload_request(fb):
                    # Reload-now control frame: queue for the SCORER
                    # thread — the reply must mean the adoption attempt
                    # finished, and only the scorer may touch the
                    # watcher/engine (reloads never race a batch).
                    try:
                        rbody = protocol.parse_reload_request(fb)
                    except WireError as e:
                        log.warning(f"[SERVE] dropping connection: {e}")
                        return
                    with self._stats_lock:
                        self._reload_frames += 1
                    self._reload_q.append((rbody["id"], writer))
                    continue
                try:
                    body = protocol.parse_request(fb)
                except WireError as e:
                    # Framing was intact (we got a whole frame) — if the
                    # body still names an id, answer an explicit 400
                    # instead of dropping: on a ROUTER connection many
                    # clients share this socket, and one client's
                    # malformed body must not sever everyone's.
                    try:
                        bad_id = protocol.frame_id(fb)
                    except WireError:
                        log.warning(f"[SERVE] dropping connection: {e}")
                        return
                    self._count_reject("bad_request")
                    writer.send(
                        protocol.build_reject(bad_id, code=400, reason=str(e))
                    )
                    continue
                req_id = body["id"]  # parse_request pinned the type
                req_trace = body.get("trace")
                reject = self._make_reject(writer, req_id)
                if "features" in body:
                    if self.spec is None:
                        self._count_reject("bad_request")
                        reject(
                            400,
                            "this server accepts text requests only "
                            "(no dataset spec configured)",
                        )
                        continue
                    try:
                        text = render_row(body["features"], self.spec.template)
                    except KeyError as e:
                        self._count_reject("bad_request")
                        reject(400, f"features missing template column {e}")
                        continue
                else:
                    text = body["text"]
                # batch_encode, not encode: it takes the native WordPiece
                # fast path when built, and is byte-identical to what the
                # predict pipeline feeds (bit-parity depends on it).
                enc = self.tok.batch_encode([text], max_len=seq_len)
                row_ids = enc["input_ids"][0]
                row_mask = enc["attention_mask"][0]
                deadline_ms = body.get("deadline_ms")
                deadline_s = (
                    float(deadline_ms) / 1e3
                    if deadline_ms is not None
                    else self.default_deadline_s
                )
                req = ScoreRequest(
                    req_id=req_id,
                    input_ids=row_ids,
                    attention_mask=row_mask,
                    reply=self._make_reply(writer, req_id, req_trace),
                    reject=reject,
                    deadline_s=deadline_s,
                    trace=req_trace,
                )
                if not self.batcher.submit(req):
                    self._count_reject("overloaded")
                    reject(
                        protocol.REJECT_OVERLOADED,
                        f"queue full ({self.batcher.max_queue} pending)",
                    )
        finally:
            with self._conn_lock:
                self._conns.discard(conn)
            writer.close()
            try:
                conn.close()
            except OSError:
                pass

    def _auth_handshake(self, conn: socket.socket) -> bool:
        """Challenge-response before the first request is read (the FL
        tier's per-connection nonce, comm/server.py): send NONCE_MAGIC +
        fresh nonce, require SCORE_AUTH_MAGIC + HMAC(key, domain + nonce).
        The handshake runs before the writer thread exists, so these are
        the connection's only writes — no interleaving to worry about.
        A short handshake deadline bounds how long an unauthenticated
        connection can hold a reader thread."""
        import os as _os

        from ..comm.wire import NONCE_LEN, NONCE_MAGIC

        nonce = _os.urandom(NONCE_LEN)
        try:
            conn.settimeout(10.0)
            framing.send_frame(conn, NONCE_MAGIC + nonce, await_ack=False)
            proof = framing.recv_frame(
                conn, send_ack=False, max_frame=MAX_REQUEST_FRAME
            )
            conn.settimeout(None)
        except (OSError, ConnectionError, WireError) as e:
            self._count_reject("auth")
            log.warning(f"[SERVE] auth handshake failed: {e}")
            return False
        if not protocol.check_auth_response(proof, self.auth_key, nonce):
            self._count_reject("auth")
            log.warning(
                "[SERVE] dropping connection: bad or missing auth proof "
                "(client must score with the matching key)"
            )
            return False
        return True

    def _make_reply(
        self, writer: _ConnWriter, req_id: int, trace: str | None = None
    ):
        def _reply(
            *, prob, round_id, batch_size, bucket, queue_ms, class_probs=None
        ):
            writer.send(
                protocol.build_reply(
                    req_id,
                    prob=prob,
                    threshold=self.threshold,
                    round_id=round_id,
                    batch_size=batch_size,
                    bucket=bucket,
                    queue_ms=queue_ms,
                    trace=trace,
                    class_probs=class_probs,
                )
            )

        return _reply

    def _make_reject(self, writer: _ConnWriter, req_id: int):
        def _reject(code: int, reason: str) -> None:
            writer.send(
                protocol.build_reject(req_id, code=code, reason=reason)
            )

        return _reject

    # ------------------------------------------------------------ score path
    def _count_reject(self, kind: str) -> None:
        with self._stats_lock:
            self._rejects[kind] += 1
        self._m_rejects[kind].inc()

    def _drain_reload_requests(self) -> None:
        """Answer queued SCORE_RELOAD frames from the scorer thread: one
        FORCED watcher poll (interval bypassed) covers every request that
        arrived since the last batch, then each gets a reply carrying the
        round now serving. No watcher configured = nothing to reload —
        answered honestly with reloaded=False."""
        if not self._reload_q:
            return
        reloaded = False
        if self.watcher is not None:
            try:
                reloaded = bool(self.watcher.poll(self.engine, force=True))
            except Exception as e:
                # The watcher's own contract is never-fatal; a surprise
                # here must not kill the scorer thread either.
                log.warning(
                    f"[SERVE] forced reload poll failed (non-fatal): {e}"
                )
        round_id = self.engine.round_id
        while True:
            try:
                req_id, writer = self._reload_q.popleft()
            except IndexError:
                break
            writer.send(
                protocol.build_reload_reply(
                    req_id, reloaded=reloaded, round_id=round_id
                )
            )

    def _score_loop(self) -> None:
        while not self._closed.is_set():
            self._drain_reload_requests()
            if self.watcher is not None:
                self.watcher.poll(self.engine)
            batch = self.batcher.next_batch(timeout=self.idle_tick_s)
            if not batch:
                continue
            now = time.monotonic()
            live: list[ScoreRequest] = []
            for r in batch:
                if r.expired(now):
                    self._count_reject("deadline")
                    r.reject(
                        protocol.REJECT_DEADLINE,
                        f"deadline of {r.deadline_s * 1e3:.1f} ms exceeded "
                        f"after {(now - r.t_enqueue) * 1e3:.1f} ms in queue",
                    )
                else:
                    live.append(r)
            if not live:
                continue
            try:
                probs, class_probs, bucket, round_id = self.engine.score(
                    np.stack([r.input_ids for r in live]),
                    np.stack([r.attention_mask for r in live]),
                )
            except Exception as e:
                # A failed dispatch must not hang the batch's clients
                # (they'd block to their socket timeouts) or kill the
                # scorer thread (the whole service): reject and move on.
                log.warning(
                    f"[SERVE] scoring dispatch failed "
                    f"({type(e).__name__}: {e}); rejecting {len(live)} "
                    "request(s)"
                )
                for r in live:
                    # Counted per request: the most alarming reject class
                    # must show in stats()/JSONL, not just client-side.
                    self._count_reject("error")
                    r.reject(500, f"scoring failed: {type(e).__name__}")
                # Flight recorder (obs/flight.py): a failed dispatch IS
                # the scoring tier's 3 a.m. moment — `infer-serve
                # --flight-dir` preserves the surrounding spans + metric
                # state (rate-limited; never fatal to the batch loop).
                recorder = obs_flight.get_global_recorder()
                if recorder is not None:
                    try:
                        recorder.maybe_dump(
                            "scoring-error",
                            extra={
                                "error": f"{type(e).__name__}: {e}"[:300],
                                "rejected": len(live),
                                "bucket_batch": len(live),
                            },
                        )
                    except OSError as dump_err:
                        log.warning(
                            "[SERVE] postmortem dump failed "
                            f"(non-fatal): {dump_err}"
                        )
                continue
            done = time.monotonic()
            n = len(live)
            # The batch's score-distribution histogram: the drift signal
            # (control/drift.py) — binned counts, never raw scores, so the
            # JSONL stays small under any traffic volume.
            batch_hist, _ = np.histogram(
                np.clip(np.asarray(probs[:n], np.float64), 0.0, 1.0),
                bins=self._hist_edges,
            )
            queue_depth = self.batcher.qsize()
            # Accumulate BEFORE replying: a synchronous client that got
            # its reply may probe stats() immediately, and every flow it
            # was answered for must already be counted — replying first
            # opens a window where scored/score_hist lag the last reply
            # (seen as a rare co-tenancy flake in the histogram test).
            with self._stats_lock:
                self._scored += n
                self._batches += 1
                self._batch_hist[n] += 1
                self._score_hist += batch_hist
                self._latencies.extend(done - r.t_enqueue for r in live)
                rejects_total = sum(self._rejects.values())
            self._m_scored.inc(n)
            self._m_batches.inc()
            self._g_queue.set(queue_depth)
            self._g_round.set(round_id)
            for r in live:
                self._h_queue_ms.observe(now - r.t_enqueue)
            # K-class heads put the full per-class softmax on the wire
            # (optional reply key — old SDKs keep reading the scalar);
            # binary replies stay byte-identical to the pre-K-class wire.
            kclass = class_probs.shape[1] > 2
            for i, (r, p) in enumerate(zip(live, probs)):
                r.reply(
                    prob=float(p),
                    round_id=round_id,
                    batch_size=n,
                    bucket=bucket,
                    queue_ms=(now - r.t_enqueue) * 1e3,
                    class_probs=(
                        class_probs[i].tolist() if kclass else None
                    ),
                )
            if self.scored_jsonl:
                import json as _json

                from ..obs.trace import append_jsonl_line

                for r, p in zip(live, probs):
                    append_jsonl_line(
                        self.scored_jsonl,
                        _json.dumps(
                            {
                                "schema": "fedtpu-scored-v1",
                                "rid": str(r.req_id),
                                "prob": round(float(p), 6),
                                "round": round_id,
                            }
                        ),
                    )
            if self.tracer is not None and (
                # Counter-stride sampling: batch 1, 1+stride, 1+2*stride,
                # ... (self._batches was already incremented above, so
                # the FIRST batch always emits — a short-lived scorer
                # still leaves a span).
                (self._batches - 1) % self._trace_stride == 0
            ):
                # One serve-batch span per SAMPLED coalesced dispatch;
                # trace from the first traced request in the batch (a
                # batch may mix traces — the per-request echo in each
                # reply keeps the exact mapping).
                trace = next(
                    (r.trace for r in live if r.trace is not None), None
                )
                self.tracer.record(
                    "serve-batch",
                    t_start=time.time() - (done - now),
                    dur_s=done - now,
                    trace=trace,
                    batch_size=n,
                    bucket=bucket,
                    round=round_id,
                    # 1 span stands for this many batches (1 = unsampled,
                    # field omitted to keep the common case compact).
                    sampled_batches=(
                        self._trace_stride
                        if self._trace_stride > 1
                        else None
                    ),
                )
            if self.metrics_jsonl:
                from ..reporting import append_metrics_jsonl

                append_metrics_jsonl(
                    self.metrics_jsonl,
                    {
                        "phase": "serve_batch",
                        "batch_size": n,
                        "bucket": bucket,
                        "round": round_id,
                        "score_ms": round((done - now) * 1e3, 3),
                        "queue_ms_max": round(
                            max((now - r.t_enqueue) for r in live) * 1e3, 3
                        ),
                        "queue_depth": queue_depth,
                        "rejects_total": rejects_total,
                        "score_hist": batch_hist.tolist(),
                    },
                )


def _flat(stats: dict) -> dict:
    """Flatten stats() for the scalar-only JSONL writer."""
    out = {}
    for k, v in stats.items():
        if isinstance(v, dict):
            for kk, vv in v.items():
                out[f"{k}_{kk}"] = vv
        else:
            out[k] = v
    return out
