"""Bucketed jit cache: score variable-size batches through fixed shapes.

A scoring service sees every batch size from 1 (a lone probe) to the
coalescing cap. Jitting on the raw size would compile a fresh XLA
program per novel size — a multi-second stall mid-traffic, per size.
Instead batches are padded up to a small ladder of bucket shapes
(default 1/8/32/128) so the service runs at most ``len(buckets)``
compilations for its whole lifetime, all of them optionally paid at
startup (``warmup()``), and every request thereafter hits a warm path.

The probability math is exactly the eval path's (train/engine.py
``eval_counts``): ``softmax(model.apply(...))`` with deterministic
apply — scalar score ``[:, 1]`` for K = 2, ``1 - [:, 0]`` for K > 2
(the same STATIC head-width branch) — and pad rows built the way
``pad_split_to_batch`` builds them — which is what makes served
probabilities bit-for-bit equal to ``fedtpu predict``'s (pinned in
tests/test_serving.py). The full per-class softmax rides along so the
serving wire can carry K-class scores (serving/protocol.py
``class_probs``).

Sharded serving (``mesh=``): with an FSDP host mesh the engine holds
params sharded per-leaf AT REST (parallel/mesh.py ``fsdp_tree_shardings``
— per-chip static bytes ~1/N) and all-gathers the weights AT USE via a
separate per-dispatch jitted program (``fsdp_gather_program`` — see its
docstring for why the gather is NOT the train step's in-body constraint:
inlined collectives shift XLA's fusion and drift the probs by 1 ulp,
breaking the crc contract below), so full-size weights exist only
transiently during a forward and every bucket program compiles the SAME
collective-free module the replicated engine runs — served probabilities
from a sharded replica are bit-identical to a replicated one's (bench
``serve_fsdp_crc_exact``). ``swap`` re-places onto the SAME
shape-deterministic layout (``fsdp_spec`` is a pure function of
(shape, n_shards)), so a rolling hot-reload reuses every warm bucket
program — the ledger's 0-recompile guarantee holds across reloads.
The shard-layout derivation is inside the ``fedtpu check`` determinism
scope: the layout must replay identically on every process, or a
restore-scatter and a reply-leaf sink would disagree about where bytes
live.

Compile counting: the Python body of a jitted function runs once per
traced shape — so a trace hook inside ``_probs`` IS a compile hook, not
a call counter. That discipline is now the repo-wide
:class:`~..obs.profile.CompileLedger` (this module pioneered it as a
local dict); each engine holds a PRIVATE ledger under the
``serving.probs`` site so ``compile_counts`` stays per-engine while the
``fedtpu_xla_*`` /metrics families aggregate process-wide.
``compile_counts`` maps (batch, seq) to trace count; the e2e test
storms mixed sizes and asserts every value == 1, and ``warmup()`` marks
the site warm so any later novel shape is flagged as a recompile.
"""

from __future__ import annotations

import threading
from typing import Any

import numpy as np

from ..config import ModelConfig
from ..models.distilbert import DDoSClassifier
from ..obs.profile import CompileLedger, maybe_step_profiler, profile_stride
from ..utils.logging import get_logger

log = get_logger()

DEFAULT_BUCKETS = (1, 8, 32, 128)


class ScoreEngine:
    """Pad-to-bucket scoring over one jitted program per (bucket, seq).

    Thread contract: ``score`` is called by the single scorer thread;
    ``swap`` may be called from the watcher/scorer; the params reference
    is swapped atomically under a lock (scoring holds whichever params it
    read at dispatch — a reload never tears a batch)."""

    def __init__(
        self,
        model_cfg: ModelConfig,
        params: Any,
        *,
        pad_id: int = 0,
        buckets: tuple[int, ...] = DEFAULT_BUCKETS,
        round_id: int = 0,
        mesh: Any = None,
    ):
        import jax

        if not buckets or any(b < 1 for b in buckets):
            raise ValueError(f"buckets {buckets} must be positive")
        self.model_cfg = model_cfg
        self.pad_id = int(pad_id)
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        self.seq_len = int(model_cfg.max_len)
        self.mesh = mesh
        self.n_shards = int(mesh.shape["data"]) if mesh is not None else 1
        # Private compile ledger (obs/profile.py): per-engine counts —
        # two engines in one process must not mix their compile-count
        # assertions — while the metric families it increments are the
        # shared process-wide fedtpu_xla_* ones.
        self.ledger = CompileLedger()
        note_compile = self.ledger.hook("serving.probs")
        # Score-path step attribution: armed only when profiling is on
        # process-wide (--profile-stride / ObsConfig.profile_stride).
        self.step_profiler = maybe_step_profiler("score")
        self._lock = threading.Lock()
        self._params = self._place(params)
        self._round_id = int(round_id)
        # Gather-at-use as its OWN jitted program (parallel/mesh.py
        # fsdp_gather_program): executed per dispatch, output dropped
        # with the forward — full-size weights still never exist at
        # rest — but the bucket programs below compile over replicated
        # inputs, collective-free. An in-body constraint gather (the
        # train step's form) splices the all-gathers into the bucket
        # module and XLA's fusion around them drifts the probs by 1 ulp
        # vs the replicated engine, which the serving crc contract
        # forbids. The gather program gets its own ledger site so a
        # swap-induced retrace of IT is flagged like a bucket retrace.
        if mesh is not None:
            from ..parallel.mesh import fsdp_gather_program

            self._gather_prog = fsdp_gather_program(
                self._params,
                mesh,
                note=self.ledger.hook("serving.gather"),
            )
        else:
            self._gather_prog = None
        model = DDoSClassifier(model_cfg)

        def _probs(p, input_ids, attention_mask):
            # Trace-time hook: this Python body runs exactly once per
            # (batch, seq) shape — each execution of the compiled program
            # skips it. The ledger note is the compile counter.
            note_compile((input_ids.shape[0], input_ids.shape[1]))
            logits = model.apply(
                {"params": p}, input_ids, attention_mask, True
            )
            class_probs = jax.nn.softmax(logits, axis=-1)
            # STATIC head-width branch, mirroring eval_counts: K = 2
            # keeps the binary scalar verbatim (bit-identical to the
            # pre-K-class serving path); K > 2 scores P(any attack).
            if int(logits.shape[-1]) == 2:
                score = class_probs[:, 1]
            else:
                score = 1.0 - class_probs[:, 0]
            return score, class_probs

        self._probs = self.ledger.timed("serving.probs", jax.jit(_probs))

    def _place(self, params: Any) -> Any:
        """Device placement honoring the engine's layout: replicated for
        a plain engine, per-leaf ``fsdp_spec`` shardings for a sharded
        one. Shape-deterministic, so every swap lands the new weights on
        the exact layout the warm programs were compiled for."""
        import jax

        if self.mesh is None:
            return jax.device_put(params)
        from ..parallel.mesh import fsdp_tree_shardings

        return jax.device_put(
            params, fsdp_tree_shardings(params, self.mesh)
        )

    @property
    def compile_counts(self) -> dict[tuple[int, int], int]:
        """(batch, seq) -> trace count, straight off the ledger (the
        pre-ledger dict's exact shape; stats() and the compile-count-
        asserted tests read it unchanged)."""
        return self.ledger.compile_counts("serving.probs")

    # ------------------------------------------------------------ versioning
    @property
    def round_id(self) -> int:
        return self._round_id

    def swap(self, params: Any, *, round_id: int) -> None:
        """Adopt a new checkpoint's params (same architecture — shapes are
        unchanged, so the compiled programs are reused as-is; a changed
        architecture needs a new engine, serving/reload.py handles that
        distinction). On a sharded engine the new params land on the SAME
        per-leaf shard layout the warm programs were compiled against
        (``fsdp_spec`` is shape-deterministic), so a rolling reload never
        retraces a bucket — the ledger flags it if one ever does."""
        new = self._place(params)
        with self._lock:
            self._params = new
            self._round_id = int(round_id)

    def snapshot(self) -> tuple[Any, int]:
        with self._lock:
            return self._params, self._round_id

    # --------------------------------------------------------------- scoring
    def bucket_for(self, n: int) -> int:
        """Smallest bucket that fits ``n`` (callers cap n at max bucket)."""
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(
            f"batch of {n} exceeds the largest bucket {self.buckets[-1]}"
        )

    def warmup(self) -> None:
        """Pay every bucket's compilation before traffic arrives, then
        mark the site warm: any later novel shape is a flagged recompile
        (obs/profile.py — the bucket ladder makes one impossible unless
        the padding discipline breaks)."""
        for b in self.buckets:
            self.score(
                np.full((b, self.seq_len), self.pad_id, np.int32),
                np.zeros((b, self.seq_len), np.int32),
            )
        # Freeze every site — the bucket ladder AND (sharded engines)
        # the gather program, whose retrace after a swap would be just
        # as much a served-latency cliff as a bucket retrace.
        self.ledger.mark_warm()
        log.info(
            f"[SERVE] warmed {len(self.buckets)} bucket programs "
            f"(batch in {self.buckets}, seq {self.seq_len})"
        )

    def score(
        self, input_ids: np.ndarray, attention_mask: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray, int, int]:
        """Score ``[n, seq]`` rows -> (float32 probs [n], per-class
        softmax [n, K], bucket, round).

        Pads up to the bucket with PAD rows exactly as
        ``pad_split_to_batch`` does for eval (pad_id ids, zero mask) and
        slices the pad rows back off — per-row results are independent of
        sibling rows, so the padded program returns the same bits the
        eval pipeline computes."""
        n = int(input_ids.shape[0])
        bucket = self.bucket_for(n)
        if input_ids.shape[1] != self.seq_len:
            raise ValueError(
                f"rows have seq {input_ids.shape[1]}, engine expects "
                f"{self.seq_len}"
            )
        # Strided step attribution (obs/profile.py): a sampled dispatch
        # splits host pad-prep / dispatch / device-execute; unsampled
        # dispatches (and profiling off) run the bare path. Re-checked
        # lazily (one lock-free int read when off) because the CLI
        # installs the stride after the engine is built.
        prof = self.step_profiler
        if prof is None and profile_stride() > 0:
            prof = self.step_profiler = maybe_step_profiler("score")
        sampled = prof.tick() if prof is not None else False
        t0 = prof.clock() if sampled else 0.0
        if n < bucket:
            pad_ids = np.full(
                (bucket - n, self.seq_len), self.pad_id, np.int32
            )
            pad_mask = np.zeros((bucket - n, self.seq_len), np.int32)
            input_ids = np.concatenate([input_ids, pad_ids])
            attention_mask = np.concatenate([attention_mask, pad_mask])
        params, round_id = self.snapshot()
        if self._gather_prog is not None:
            # Gather AT USE: reconstruct full-size weights for this
            # dispatch only — ``params`` here is a local that dies with
            # the call, so the gathered tree is freed after the forward.
            params = self._gather_prog(params)
        ids = np.ascontiguousarray(input_ids, np.int32)
        mask = np.ascontiguousarray(attention_mask, np.int32)
        if sampled:
            prof.note_host(prof.clock() - t0)
            t1 = prof.clock()
            probs, class_probs = self._probs(params, ids, mask)
            prof.note_dispatch(prof.clock() - t1)
            prof.fence(probs)
        else:
            probs, class_probs = self._probs(params, ids, mask)
        return (
            np.asarray(probs)[:n],
            np.asarray(class_probs)[:n],
            bucket,
            round_id,
        )
