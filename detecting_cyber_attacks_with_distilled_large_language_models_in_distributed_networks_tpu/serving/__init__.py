"""Online inference service: dynamic-batching TCP scoring with hot reload.

The reference trains and evaluates but never deploys (reference
client1.py:379-400); ``fedtpu predict`` closed that gap only for offline
CSVs. This package is the live path from "federated model" to "detector
answering flow queries": a TCP service (``fedtpu infer-serve``) that
accepts flow records over the existing length-framed wire
(comm/framing.py), tokenizes them with the native WordPiece path, and
scores them through a dynamic micro-batcher whose batches are drawn from
a small set of fixed bucket shapes — so XLA compiles one program per
(bucket, seq) and every request thereafter hits a warm jitted path.

Layers (each its own module, composable and unit-testable):

* :mod:`.protocol` — request/reply/reject frame codecs over the scoring
  magics (comm/wire.py ``SCORE_*``).
* :mod:`.batcher`  — bounded request queue + gather-window coalescing
  (admission control happens HERE: a full queue is an immediate reject,
  never unbounded latency).
* :mod:`.engine`   — the bucketed jit cache: pad to the smallest bucket
  that fits, score through one traced-once-per-shape program, with a
  trace-time compile-count hook tests and ops can assert on.
* :mod:`.reload`   — checkpoint watcher: picks up new federated rounds
  between batches (reusing cli/predict's ``_restore_predict_params``)
  so the detector improves every FL round without a restart.
* :mod:`.server`   — the accept loop / scorer thread wiring + telemetry
  (per-request queue wait, batch size, model round; p50/p95/p99 on the
  metrics-JSONL channel).
* :mod:`.client`   — SDK + load generator shared by tests and bench.py.
"""

from .batcher import MicroBatcher, ScoreRequest
from .client import (
    AsyncScoringClient,
    PipelinedScoringClient,
    ScoreRejected,
    ScoringClient,
    fetch_stats,
    load_arrival_trace,
    run_load,
)
from .engine import ScoreEngine
from .protocol import (
    build_reject,
    build_reply,
    build_request,
    parse_reject,
    parse_reply,
    parse_request,
)
from .reload import CheckpointWatcher, RegistryWatcher
from .server import ScoringServer

__all__ = [
    "AsyncScoringClient",
    "CheckpointWatcher",
    "RegistryWatcher",
    "MicroBatcher",
    "PipelinedScoringClient",
    "ScoreEngine",
    "ScoreRejected",
    "ScoreRequest",
    "ScoringClient",
    "ScoringServer",
    "build_reject",
    "build_reply",
    "build_request",
    "fetch_stats",
    "load_arrival_trace",
    "parse_reject",
    "parse_reply",
    "parse_request",
    "run_load",
]
