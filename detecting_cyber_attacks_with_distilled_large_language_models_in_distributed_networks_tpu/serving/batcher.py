"""Dynamic micro-batcher: bounded admission + gather-window coalescing.

Host-side only (no JAX): connection threads ``submit()`` tokenized
requests; the single scorer thread pulls coalesced lists with
``next_batch()``. Two decisions live here and nowhere else:

* **Admission control.** The queue is bounded. A submit against a full
  queue fails immediately — the caller answers with the explicit reject
  frame — so overload degrades to fast, honest 503s instead of a latency
  cliff (the FL-server hot-path lesson of arXiv:2307.06561: backpressure
  must be designed in, not discovered).
* **Coalescing.** ``next_batch`` blocks for the first request, then keeps
  gathering until either ``max_batch`` requests are in hand or the
  ``gather_window`` since the first request elapses. Concurrent clients
  land in one padded bucket dispatch; a lone request pays at most the
  window (default a few ms) on top of its own score time.

Deadline bookkeeping rides each request (``expired()``); enforcement is
the scorer's job — it holds the moment closest to dispatch.
"""

from __future__ import annotations

import queue
import time
from dataclasses import dataclass, field
from typing import Any, Callable


@dataclass
class ScoreRequest:
    """One tokenized flow awaiting a scorer slot.

    ``reply``/``reject`` are bound by the connection handler to its
    socket (with the per-connection write lock closed over); the scorer
    never touches sockets directly."""

    req_id: int
    input_ids: Any  # np.int32 [L]
    attention_mask: Any  # np.int32 [L]
    reply: Callable[..., None]
    reject: Callable[[int, str], None]
    deadline_s: float | None = None  # relative budget from t_enqueue
    #: Optional obs trace id (obs/trace.py) the request carried; echoed
    #: in the reply so a caller can correlate its spans with the batch's.
    trace: str | None = None
    t_enqueue: float = field(default_factory=time.monotonic)

    def expired(self, now: float | None = None) -> bool:
        if self.deadline_s is None:
            return False
        return (time.monotonic() if now is None else now) >= (
            self.t_enqueue + self.deadline_s
        )


class MicroBatcher:
    """Bounded queue + gather-window coalescing (see module docstring)."""

    def __init__(
        self,
        *,
        max_batch: int = 128,
        max_queue: int = 1024,
        gather_window_s: float = 0.005,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch={max_batch} must be >= 1")
        if max_queue < max_batch:
            # A queue smaller than one batch could never fill a bucket.
            raise ValueError(
                f"max_queue={max_queue} must be >= max_batch={max_batch}"
            )
        self.max_batch = max_batch
        self.max_queue = max_queue
        self.gather_window_s = float(gather_window_s)
        self._q: queue.Queue[ScoreRequest] = queue.Queue(maxsize=max_queue)

    def submit(self, req: ScoreRequest) -> bool:
        """Admit a request. False = queue full (caller sends the 503-style
        reject); never blocks the connection thread."""
        try:
            self._q.put_nowait(req)
            return True
        except queue.Full:
            return False

    def qsize(self) -> int:
        return self._q.qsize()

    def next_batch(self, timeout: float | None = 0.1) -> list[ScoreRequest]:
        """Blocking coalesce: wait up to ``timeout`` for the first request
        (empty list on timeout — the scorer's idle tick, where reload
        polls happen), then gather until ``max_batch`` or the window
        closes. The window is anchored at the FIRST request so a steady
        trickle cannot stall a batch indefinitely."""
        try:
            first = self._q.get(timeout=timeout)
        except queue.Empty:
            return []
        batch = [first]
        window_end = time.monotonic() + self.gather_window_s
        while len(batch) < self.max_batch:
            remaining = window_end - time.monotonic()
            if remaining <= 0:
                break
            try:
                batch.append(self._q.get(timeout=remaining))
            except queue.Empty:
                break
        return batch
