"""Hot checkpoint reload: the detector improves every FL round, live.

The federated loop writes a checkpoint per round (train/checkpoint.py);
without this module the scoring service would serve round N's weights
until an operator restarted it — exactly the train/deploy gap the
reference never closed. The watcher polls the checkpoint directory
BETWEEN batches (the scorer's idle tick calls ``poll()``; no watcher
thread races the scorer) and, on a new step, restores through the same
``_restore_predict_params`` path ``fedtpu predict`` uses — federated
FedState and local TrainState checkpoints both, with the same
vocab/architecture validation — then swaps the engine's params
atomically. In-flight batches finish on the old weights; the next batch
serves the new round, and every reply names the round that scored it.

Cheap new-step detection: orbax finalizes a step by renaming its tmp dir
(``<step>.orbax-checkpoint-tmp-*``) to the bare ``<step>`` — so a
pure-digit directory entry is a completed step, and the poll is one
``os.scandir`` with no CheckpointManager construction on the idle path.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable

from ..utils.logging import get_logger

log = get_logger()

#: restore_fn contract: step (None = whatever is latest; surfaces the
#: clean "no checkpoint found" error on an empty directory) ->
#: (model_cfg, params, round_id).
RestoreFn = Callable[[int | None], tuple[Any, Any, int]]


def latest_finalized_step(ckpt_dir: str) -> int | None:
    """Largest completed orbax step in ``ckpt_dir`` (None when empty /
    missing). Pure-digit entries only — tmp dirs carry a suffix."""
    try:
        entries = os.scandir(ckpt_dir)
    except OSError:
        return None
    steps = [
        int(e.name)
        for e in entries
        if e.name.isdigit() and e.is_dir(follow_symlinks=False)
    ]
    return max(steps, default=None)


def checkpoint_restorer(cfg, tok, *, mesh=None) -> RestoreFn:
    """Bind the predict-path restore to (config, tokenizer): returns a
    ``RestoreFn`` that restores the latest finalized checkpoint and reads
    its round id from the SAME step's metadata — the round number for
    federated checkpoints, the step id for local ones. One snapshot for
    params and round id: reading "latest" twice around a params restore
    would let a round finalized in between label old weights with the new
    round id (replies must name the round that actually scored them).

    ``mesh`` (a sharded engine's FSDP host mesh) makes every restore —
    the startup one AND each hot reload's — scatter checkpoint leaves
    straight onto their shards, so a mid-traffic reload of a model bigger
    than one chip never materializes the full tree on a single device."""
    from ..cli.predict import _restore_predict_params
    from ..train.checkpoint import Checkpointer
    from ..train.engine import Trainer

    def restore(step: int | None) -> tuple[Any, Any, int]:
        with Checkpointer(cfg.checkpoint_dir) as ckpt:
            actual = ckpt.latest_step()
            pin = actual if actual is not None else step
            meta = ckpt.restore_meta(step=pin) if pin is not None else {}
        trainer = Trainer(cfg.model, cfg.train, pad_id=tok.pad_id)
        # Pinned to the step whose metadata was just read; if orbax GC
        # removes it mid-restore this raises and the watcher retries. A
        # still-None pin (empty directory) passes through so the predict
        # path raises its clean "no checkpoint found" — not a confusing
        # architecture-mismatch report against a step that never existed.
        model_cfg, params = _restore_predict_params(
            cfg, tok, trainer, ckpt_dir=cfg.checkpoint_dir, step=pin, mesh=mesh
        )
        return model_cfg, params, int(meta.get("round", pin))

    return restore


class CheckpointWatcher:
    """Poll-on-idle reload driver (single-threaded with the scorer).

    ``poll(engine)`` rate-limits itself to ``poll_interval_s``, detects a
    new finalized step, restores, and either swaps the params in place
    (same architecture) or reports the new config so the server can
    rebuild the engine. A failed restore (e.g. the checkpoint vanished
    under GC mid-restore) logs and leaves the serving params untouched —
    reload is an optimization; the service must never die for it. A
    transiently failing step is retried on later polls (up to
    ``max_retries``) before being written off: the FINAL federated
    round's checkpoint has no newer step coming after it, so marking it
    seen on the first blip would strand the service on stale weights
    forever while reload looked healthy."""

    def __init__(
        self,
        ckpt_dir: str,
        restore_fn: RestoreFn,
        *,
        poll_interval_s: float = 2.0,
        max_retries: int = 5,
    ):
        self.ckpt_dir = ckpt_dir
        self.restore_fn = restore_fn
        self.poll_interval_s = float(poll_interval_s)
        self.max_retries = int(max_retries)
        self._last_poll = 0.0
        self._seen_step: int | None = None
        self._fail_step: int | None = None
        self._fail_count = 0
        self._primed = False
        self.reload_count = 0

    @property
    def primed(self) -> bool:
        return self._primed

    def prime(self, step: int | None = None) -> None:
        """Record the step already serving (skip a spurious first reload).

        Callers that restored a specific step should pass it: priming by
        directory scan instead would mark any step finalized between the
        restore and this call as already-seen — stale weights served
        until the NEXT round lands (or forever, if training finished)."""
        self._seen_step = (
            latest_finalized_step(self.ckpt_dir) if step is None else step
        )
        self._primed = True

    def poll(self, engine, *, force: bool = False) -> bool:
        """One idle-tick check; True when a new checkpoint was adopted.
        ``force`` bypasses the poll-interval rate limit — the
        SCORE_RELOAD control frame's drain-then-reload-NOW semantics."""
        now = time.monotonic()
        if not force and now - self._last_poll < self.poll_interval_s:
            return False
        self._last_poll = now
        step = latest_finalized_step(self.ckpt_dir)
        if step is None or (
            self._seen_step is not None and step <= self._seen_step
        ):
            return False
        try:
            model_cfg, params, round_id = self.restore_fn(step)
        except (Exception, SystemExit) as e:
            # SystemExit included: the predict-path restore raises it for
            # operator-facing CLI errors (missing/mismatched checkpoint),
            # and an uncaught SystemExit would silently end the scorer
            # thread — the service must outlive a bad reload.
            if self._fail_step != step:
                self._fail_step, self._fail_count = step, 0
            self._fail_count += 1
            if self._fail_count >= self.max_retries:
                # Persistent failure (corrupt/incompatible step): stop
                # burning every poll on it; a NEWER step still reloads.
                self._seen_step = step
            log.warning(
                f"[SERVE] checkpoint reload from {self.ckpt_dir} (step "
                f"{step}) failed ({type(e).__name__}: {e}); keeping the "
                f"serving weights (attempt {self._fail_count}/"
                f"{self.max_retries}"
                + (
                    ", giving this step up"
                    if self._fail_count >= self.max_retries
                    else ", will retry"
                )
                + ")"
            )
            return False
        self._fail_step, self._fail_count = None, 0
        self._seen_step = step
        if model_cfg != engine.model_cfg:
            log.warning(
                f"[SERVE] checkpoint at step {step} declares a different "
                "architecture than the serving engine; skipping hot reload "
                "(restart the service to change model shapes)"
            )
            return False
        engine.swap(params, round_id=round_id)
        self.reload_count += 1
        log.info(
            f"[SERVE] hot-reloaded checkpoint step {step} "
            f"(model round {round_id})"
        )
        return True


class RegistryWatcher:
    """Pointer-following reload: serve ONLY what the control plane promoted.

    The checkpoint watcher above trusts the training tier completely —
    whatever step lands in the directory gets served. With a model
    registry (registry/) in the loop, that trust moves to the eval gate:
    this watcher follows the registry's atomically-swapped serving
    pointer, so an unevaluated or gate-rejected candidate can never reach
    traffic, and a ``registry rollback`` takes effect within one poll
    interval with no serving restart.

    Same duck type as :class:`CheckpointWatcher` (``poll(engine)`` /
    ``prime()`` / ``primed`` / ``reload_count``), so the scoring server
    drives either without knowing which deployment shape it is in."""

    def __init__(self, registry, *, poll_interval_s: float = 2.0):
        self.registry = registry
        self.poll_interval_s = float(poll_interval_s)
        self._last_poll = 0.0
        self._seen: str | None = None
        # Incompatible artifacts are NOT marked seen (a rollback to a
        # compatible one must still be adopted), so dedup their warning
        # here — a 2 s poll would otherwise log the same line ~43k
        # times/day until an operator intervened.
        self._warned: str | None = None
        self._primed = False
        self.reload_count = 0

    @property
    def primed(self) -> bool:
        return self._primed

    def prime(self, artifact: str | None = None) -> None:
        """Record the artifact already serving (the one the caller just
        loaded); None primes from the current pointer."""
        if artifact is None:
            info = self.registry.serving_info()
            artifact = info["artifact"] if info else None
        self._seen = artifact
        self._primed = True

    def poll(self, engine, *, force: bool = False) -> bool:
        """One idle-tick check; True when a newly promoted (or rolled-
        back-to) artifact was adopted. Any registry error leaves the
        serving params untouched — reload is an optimization; the
        service must never die for it. ``force`` bypasses the poll
        interval (the SCORE_RELOAD control frame)."""
        now = time.monotonic()
        if not force and now - self._last_poll < self.poll_interval_s:
            return False
        self._last_poll = now
        try:
            info = self.registry.serving_info()
        except Exception as e:
            log.warning(f"[SERVE] registry pointer read failed: {e}")
            return False
        if info is None or info.get("artifact") == self._seen:
            return False
        aid = info["artifact"]
        try:
            manifest = self.registry.manifest(aid)
            mc = manifest.get("model_config")
            if mc is not None:
                import dataclasses as _dc

                if mc != _dc.asdict(engine.model_cfg):
                    # Do NOT mark seen: the operator may roll back to a
                    # compatible artifact, which must still be adopted.
                    if self._warned != aid:
                        self._warned = aid
                        log.warning(
                            f"[SERVE] serving artifact {aid} declares a "
                            "different architecture than the engine; "
                            "skipping hot swap (restart the service to "
                            "change shapes)"
                        )
                    return False
            params = self.registry.load_params(aid)
            # Checkpoint/restore's compatibility predicate, reused: same
            # pytree structure and per-leaf shapes, dtype-tolerant.
            from ..train.checkpoint import _shapes_match

            if mc is None and not _shapes_match(
                engine.snapshot()[0], params
            ):
                # No recorded architecture to compare (older artifact):
                # the param tree itself is the claim — a mismatched tree
                # would swap in fine and then fail EVERY batch until an
                # operator rolls back.
                if self._warned != aid:
                    self._warned = aid
                    log.warning(
                        f"[SERVE] serving artifact {aid} has a different "
                        "param tree than the engine (no model_config "
                        "recorded); skipping hot swap"
                    )
                return False
            # Adoption inside the guard too: device_put in swap() can
            # fail transiently (e.g. an OOM while two model copies
            # coexist) and the scorer thread must outlive it.
            engine.swap(params, round_id=int(manifest.get("round", 0)))
        except Exception as e:
            log.warning(
                f"[SERVE] reload of serving artifact {aid} failed "
                f"({type(e).__name__}: {e}); keeping the serving weights"
            )
            return False
        self._seen = aid
        self._warned = None
        self.reload_count += 1
        log.info(
            f"[SERVE] hot-swapped to promoted artifact {aid} "
            f"(round {manifest.get('round')})"
        )
        return True
