"""FedSeqTrainer: the federated trainer over a ``clients x data x seq``
mesh — sequence-parallel (ring attention) local training with the full
FederatedTrainer surface.

Presents exactly the surface ``cmd_federated`` and ``FederatedTrainer.run``
drive (init_state / fit_local / prepare_eval / evaluate_clients /
participation_mask / aggregate / checkpointed FedState), so every product
feature around the trainer — eval + metrics CSVs/plots, ROC/PR,
checkpoint/resume, DP-FedAvg, FedOpt, FedProx (the proximal term rides the
fedseq loss, parallel/fedseq.py), personalization (the scope-matched side
trainer is this class again), partial participation, fault masks — works
under sequence parallelism without its own code path. Multi-host composes
too: clients lay process-major over hosts (parallel/multihost.py
make_global_seq_mesh), so the latency-critical seq ring and the data-axis
psum stay on each host's ICI and only the round's FedAvg pmean crosses
DCN — the v4-64 north-star shape (clients over DCN x seq ring on ICI).
The reference has no long-context story at all (fixed L=128,
client1.py:27); this is the framework's owed composition (VERDICT r2 #2,
completed r4; multi-host in r5 per VERDICT r4 #1).

Dropout trains ON (the reference's head dropout 0.3, client1.py:57):
masks are hash-keyed on global coordinates, so the trajectory is invariant
to the seq-axis shard count (ops/hash_dropout.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config import ExperimentConfig
from ..parallel.fedseq import build_fedseq_steps, make_seq_mesh
from ..utils.logging import get_logger
from .federated import FederatedTrainer

log = get_logger()


class FedSeqTrainer(FederatedTrainer):
    """N clients x batch shards x sequence shards, one SPMD program."""

    def __init__(self, cfg: ExperimentConfig, *, pad_id: int = 0, mesh=None):
        # seq=1 runs the identical program on a degenerate ring — the
        # anchor for shard-count-invariance tests. Production runs use the
        # cheaper 2-axis FederatedTrainer when seq==1 (cli/federated.py).
        if cfg.mesh.seq < 1:
            raise ValueError("FedSeqTrainer needs mesh.seq >= 1")
        # The model must take the ring path inside the 3-axis shard_map.
        if (
            cfg.model.attention_impl != "ring"
            or cfg.model.ring_axis != "seq"
        ):
            cfg = dataclasses.replace(
                cfg,
                model=dataclasses.replace(
                    cfg.model, attention_impl="ring", ring_axis="seq"
                ),
            )
        if cfg.model.max_len % cfg.mesh.seq:
            raise ValueError(
                f"model.max_len={cfg.model.max_len} must divide into "
                f"mesh.seq={cfg.mesh.seq} equal sequence chunks"
            )
        if mesh is None:
            if jax.process_count() > 1:
                # Multi-host: clients over DCN x seq ring on ICI — clients
                # laid process-major so every ring ppermute and data-axis
                # psum stays inside one host; only the round's FedAvg
                # pmean crosses DCN (parallel/multihost.py).
                from ..parallel.multihost import make_global_seq_mesh

                mesh = make_global_seq_mesh(
                    cfg.mesh.clients, cfg.mesh.data, cfg.mesh.seq
                )
            else:
                mesh = make_seq_mesh(
                    cfg.mesh.clients, cfg.mesh.data, cfg.mesh.seq
                )
        log.info(
            f"[FEDSEQ] mesh {cfg.mesh.clients}x{cfg.mesh.data}x"
            f"{cfg.mesh.seq} (clients x data x seq), ring attention over "
            f"{cfg.model.max_len // cfg.mesh.seq}-token chunks"
            + (
                f"; {jax.process_count()} hosts, rings on-host"
                if jax.process_count() > 1
                else ""
            )
        )
        super().__init__(cfg, pad_id=pad_id, mesh=mesh)

    def _build_steps(self) -> None:
        # The 2-axis builders stay for everything batch-free — fedavg/DP/
        # FedOpt aggregation, opt init, replication — their P('clients')
        # shardings are valid on the 3-axis mesh (replicated over seq).
        # jit is lazy, so the dense train/eval programs they also build
        # never compile; the fedseq programs below shadow them.
        super()._build_steps()
        steps = build_fedseq_steps(
            self.cfg, self.model, self.optimizer, self.mesh
        )
        self.train_step = steps.train_step
        self.eval_step = steps.eval_step
        self._build_ragged_step = steps.build_ragged_step
        self._ragged_train_step = None
        # Client-packing fast path, 3-axis variant: per-client ring-path
        # step with no client axis and no inner vmap (parallel/fedseq.py
        # make_fedseq_packed_loss) — shadows the dense packed builder the
        # super() call installed.
        self._build_packed_step = steps.build_packed_step
        self._packed_step = None

    def _feed(self, batch: dict[str, Any]) -> dict[str, Any]:
        """[C, B, L] token arrays shard over (clients, data, seq); [C, B]
        row arrays (labels/valid/warmup_step) over (clients, data).
        Multi-host: each process supplies only ITS client rows, assembled
        into global arrays (multihost.global_rows)."""
        from ..parallel.multihost import global_rows

        out = {}
        for k, v in batch.items():
            spec = (
                P("clients", "data", "seq")
                if getattr(v, "ndim", 0) >= 3
                else P("clients", "data")
            )
            out[k] = global_rows(
                NamedSharding(self.mesh, spec), np.asarray(v), self.C
            )
        return out

    def fit_local(self, state, stacked_train, **kw):
        B = (
            self.cfg.data.batch_size
            if kw.get("batch_size") is None
            else kw["batch_size"]
        )
        d = self.mesh.devices.shape[1]
        if B % d:
            raise ValueError(
                f"batch_size={B} must divide over the data axis ({d})"
            )
        return super().fit_local(state, stacked_train, **kw)

    def _trace_attrs(self) -> dict:
        """Obs span attributes: the 3-axis product path's layout — seq
        shard count and ring chunk size — so a merged timeline can
        attribute fedseq rounds to their ring configuration (the
        fedseq-MFU-residual instrument rides the same identity in
        bench.py's decomposition fields)."""
        return {
            "path": "fedseq",
            "clients": self.C,
            "seq": self.cfg.mesh.seq,
            "ring_chunk": self.cfg.model.max_len // self.cfg.mesh.seq,
        }
