"""Federated multi-round training driver — SPMD over a ``clients`` mesh axis.

Replaces the reference's entire process topology (client1.py + client2.py +
server.py: N near-identical scripts, a threaded TCP server, gzip-pickled
state dicts, two ports, retry budgets) with:

* one stacked parameter pytree ``[C, ...]`` sharded over the ``clients`` mesh
  axis — client c's replica lives on its own submesh;
* one jitted, vmapped train step — every client advances in lockstep, each on
  its private data shard; within a client, batch rows shard over the ``data``
  axis and XLA psums the gradients;
* the round boundary is ``fedavg`` (parallel/fedavg.py) — a single collective,
  no server process, no serialization, no sockets;
* per-client local-vs-aggregated evaluation identical in shape to the
  reference flow (train -> local eval -> aggregate -> aggregated eval,
  client1.py:379-404).

The reference achieves multi-round FL only by re-running processes with
warm-start .pth files (client1.py:375-377); here rounds are a loop, with
optimizer state optionally reset per round to mirror the reference's
fresh-Adam-per-run semantics (FedConfig.reset_optimizer_each_round).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..config import ExperimentConfig
from ..data.pipeline import StackedClients, TokenizedSplit
from ..models.distilbert import DDoSClassifier, init_params
from ..obs.profile import maybe_step_profiler, note_memory, profiled_step_iter
from ..parallel.fedavg import stack_params
from ..parallel.mesh import FedShardings, make_mesh
from ..train.engine import make_optimizer
from ..utils.logging import get_logger, phase

# Re-exports: batch iterators, eval plumbing, and jitted-step builders
# split out of this file; importing them from here keeps the historical API.
from .batches import (  # noqa: F401
    PrefetchSlot,
    federated_batches,
    federated_batches_ragged,
)
from .fedeval import (  # noqa: F401
    PreparedEval,
    evaluate_stacked,
    stack_eval_splits,
)
from .fedsteps import (  # noqa: F401
    FedState,
    aggregate_round,
    cached_federated_steps,
)

log = get_logger()


@dataclass
class RoundRecord:
    round: int
    epoch_losses: np.ndarray  # [E, C]
    local_metrics: list[dict]
    aggregated_metrics: list[dict] = field(default_factory=list)


class FederatedTrainer:
    """N-client FedAvg on a ``clients x data`` mesh."""

    def __init__(self, cfg: ExperimentConfig, *, pad_id: int = 0, mesh=None):
        self.cfg = cfg
        self.C = cfg.fed.num_clients
        self.pad_id = pad_id
        # Multi-host: the caller bootstraps jax.distributed (multihost.py
        # initialize) and passes a global mesh (make_global_mesh); each
        # process then feeds only its own client rows. Single process is the
        # degenerate case of the same code path.
        self.P = jax.process_count()
        if mesh is not None:
            self.mesh = mesh
        else:
            rows = cfg.mesh.clients
            n_dev = len(jax.devices())
            if self.P == 1 and rows * cfg.mesh.data > n_dev:
                # Fit the mesh to the hardware: stack several logical client
                # replicas per row rather than refusing to run (tested up to
                # 64 logical clients on 8 rows).
                from ..parallel.mesh import fit_clients_axis

                rows = fit_clients_axis(self.C, cfg.mesh.data, n_dev)
                log.info(
                    f"[FED] {self.C} clients on {n_dev} device(s): mesh "
                    f"{cfg.mesh.clients}x{cfg.mesh.data} -> "
                    f"{rows}x{cfg.mesh.data} "
                    f"({self.C // rows} client replicas per row)"
                )
            self.mesh = make_mesh(
                rows, cfg.mesh.data, axis_names=cfg.mesh.axis_names
            )
        if self.P > 1:
            from ..parallel.multihost import local_client_slice

            mesh_rows = self.mesh.devices.shape[0]
            self.client_offset = local_client_slice(self.mesh).start * (
                self.C // mesh_rows
            )
        else:
            self.client_offset = 0
        self.sh = FedShardings(self.mesh)
        self.model = DDoSClassifier(cfg.model)
        self.optimizer = make_optimizer(cfg.train)
        # Observability (obs/trace.py): set by the CLI (or any caller) to
        # emit per-round client-local/agg phase spans; None by default —
        # the global tracer (set_global_tracer) is the fallback so
        # embedded constructions need no plumbing.
        self.tracer = None
        # Step-time attribution (obs/profile.py): None unless profiling
        # is armed process-wide; re-checked at fit time because the CLI
        # installs the stride after trainers are built.
        self.step_profiler = maybe_step_profiler("train")
        # One-slot epoch prefetch (train/batches.PrefetchSlot), armed
        # by prefetch_epoch while the round's wire exchange is in flight;
        # _epoch_batches consumes a matching key, so the batch sequence
        # is identical prefetched or not.
        self._prefetch = PrefetchSlot()
        self._build_steps()

    # ---------------------------------------------------------- jitted steps
    def _build_steps(self) -> None:
        """Delegates jitted-program construction to fedsteps (pure function
        of config/model/optimizer/shardings); keeps only the lifecycle
        state this trainer owns — lazy ragged compilation and the DP noise
        seed (OS entropy + multi-host agreement)."""
        steps = cached_federated_steps(self.cfg, self.mesh)
        self.train_step = steps.train_step
        self.eval_step = steps.eval_step
        self.fedavg_step = steps.fedavg_step
        self.server_tx = steps.server_tx
        self.server_agg_step = steps.server_agg_step
        self.dp_fedavg_step = steps.dp_fedavg_step
        self._opt_init = steps.opt_init
        self._replicate = steps.replicate
        # Built on first ragged fit_local (equal-client runs never pay the
        # extra compilation).
        self._build_ragged_step = steps.build_ragged_step
        self._ragged_train_step = None
        # Client-packing fast path (single-device mesh): built lazily on
        # the first eligible fit_local.
        self._build_packed_step = steps.build_packed_step
        self._packed_step = None
        if self.dp_fedavg_step is not None:
            # Noise seed: fresh OS entropy (the training seed is public
            # config — noise derived from it could be regenerated and
            # subtracted, voiding the guarantee). dp_seed overrides for
            # reproducible tests. Multi-host: everyone adopts process 0's
            # draw so the SPMD noise is globally consistent.
            seed = self.cfg.fed.dp_seed
            if seed is None:
                import os as _os

                seed = int.from_bytes(_os.urandom(8), "little") >> 1
            if self.P > 1:
                from ..parallel.multihost import allgather_hosts

                seed = int(allgather_hosts(seed)[0])
            self._dp_seed = seed

    def _host(self, tree: Any) -> Any:
        """np.asarray over a (possibly clients-sharded) pytree."""
        if self.P > 1:
            tree = self._replicate(tree)
        return jax.tree.map(np.asarray, tree)

    def _feed(self, batch: dict[str, np.ndarray]) -> dict[str, Any]:
        """Process-local [C_local, B, ...] host batch -> global sharded feed."""
        from ..parallel.multihost import global_batch

        return global_batch(self.sh.batch, batch, self.C)

    # -------------------------------------------------------------- lifecycle
    def init_state(self, seed: int | None = None, params: Any | None = None) -> FedState:
        """All clients start from the same initial params — the reference's
        condition (every client loads the same pretrained DistilBERT,
        client1.py:56)."""
        seed = self.cfg.train.seed if seed is None else seed
        impl = self.cfg.train.prng_impl
        rng = jax.random.key(seed, impl=impl)
        if params is None:
            params = init_params(self.model, self.cfg.model, rng)
        C = self.C

        rngs = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
            jax.random.fold_in(rng, 7), jnp.arange(C)
        )
        if self.P == 1:
            stacked_params = jax.device_put(
                stack_params(params, C), self.sh.client
            )
        else:
            # Every process computed identical params from the same seed
            # (the reference's shared-pretrained-start, client1.py:56);
            # assemble the global [C, ...] stack from those replicas.
            from ..parallel.multihost import global_array_from_replicated

            stacked_params = jax.tree.map(
                lambda x: global_array_from_replicated(
                    self.sh.client,
                    np.broadcast_to(np.asarray(x)[None], (C, *np.shape(x))),
                ),
                params,
            )
            rngs = jax.random.wrap_key_data(
                global_array_from_replicated(
                    self.sh.client, np.asarray(jax.random.key_data(rngs))
                ),
                impl=impl,
            )
        opt_state = self._opt_init(stacked_params)
        server_opt = None
        if self.server_tx is not None:
            # Single-model fp32 state (replicated); every host computes the
            # identical init from the identical starting params.
            server_opt = self.server_tx.init(
                jax.tree.map(lambda x: jnp.asarray(x, jnp.float32), params)
            )
            if self.P > 1:
                # Like params/rngs above: promote host-local replicas to
                # global replicated arrays, or the jitted steps reject the
                # process-local device placement.
                from ..parallel.multihost import global_array_from_replicated

                server_opt = jax.tree.map(
                    lambda x: global_array_from_replicated(
                        self.sh.replicated, np.asarray(x)
                    ),
                    server_opt,
                )
        return FedState(
            params=stacked_params,
            opt_state=opt_state,
            step=jnp.zeros((), jnp.int32),
            rngs=rngs,
            server_opt=server_opt,
        )

    def reset_optimizer(self, state: FedState) -> FedState:
        return state._replace(opt_state=self._opt_init(state.params))

    def personalize(
        self,
        state: FedState,
        stacked_train,
        *,
        epochs: int | None = None,
        scope: str | None = None,
    ) -> tuple[FedState, "np.ndarray"]:
        """FedAvg + local fine-tuning: train each client's replica on its
        own shard from the current (typically just-aggregated) params,
        WITHOUT a closing aggregate — the result is per-client
        personalized models, the third evaluation phase next to the
        reference's local/aggregated pair. ``scope="head"`` freezes the
        shared encoder and adapts only the classifier (FedPer); ``"full"``
        fine-tunes everything (FedAvg+FT). Runs the same SPMD fit as a
        round, so it composes with ragged stacks and multi-host meshes."""
        from dataclasses import replace as dc_replace

        epochs = self.cfg.fed.personalize_epochs if epochs is None else epochs
        scope = self.cfg.fed.personalize_scope if scope is None else scope
        if epochs <= 0:
            raise ValueError("personalize needs epochs > 0")
        if scope not in ("full", "head"):
            raise ValueError(f"personalize scope {scope!r} must be full|head")
        # Build a scope-matched trainer in EITHER direction: head scope on
        # an all-params config, or full scope on a linear-probing
        # (trainable='head') base config. type(self) keeps the subclass'
        # step builders — a FedSeqTrainer personalizes with the same
        # 3-axis sequence-parallel programs it trained with.
        want_trainable = "head" if scope == "head" else "all"
        if self.cfg.train.trainable != want_trainable:
            ptrainer = type(self)(
                dc_replace(
                    self.cfg,
                    train=dc_replace(self.cfg.train, trainable=want_trainable),
                ),
                pad_id=self.pad_id,
                mesh=self.mesh,
            )
        else:
            ptrainer = self
        # Personalization is a SIDE BRANCH: the jitted steps donate their
        # input buffers, so train on copies of the leaves that survive
        # into the branch (params/rngs/step/server state) — the caller's
        # aggregate state stays alive for reporting/checkpointing. The
        # optimizer state is NOT copied: it is rebuilt fresh under the
        # (possibly masked) personal optimizer (same policy as the
        # per-round reset), and copying the stacked Adam moments first
        # would transiently double the largest allocation on the mesh.
        import jax.numpy as jnp

        params = jax.tree.map(jnp.copy, state.params)
        state = state._replace(
            params=params,
            opt_state=ptrainer._opt_init(params),
            step=jnp.copy(state.step),
            rngs=jnp.copy(state.rngs),
            server_opt=jax.tree.map(jnp.copy, state.server_opt),
        )
        return ptrainer.fit_local(state, stacked_train, epochs=epochs)

    # ---------------------------------------------------------------- phases
    def _epoch_batches(self, stacked_train, bs: int, epoch: int):
        """One epoch's ``[C, B, ...]`` iterator, served from an armed
        matching prefetch when available (same permutation keying, so
        the sequence is identical either way)."""
        it = self._prefetch.consume((id(stacked_train), int(epoch), bs))
        if it is not None:
            return it
        return self._epoch_iterator(stacked_train, bs, epoch)

    def _epoch_iterator(self, stacked_train, bs: int, epoch: int):
        """The epoch's lockstep iterator — the SINGLE derivation of its
        permutation keying, shared by the live path and the armed
        prefetch so a prefetched head can never train on different
        batches."""
        return federated_batches(
            stacked_train,
            bs,
            seed=self.cfg.train.seed,
            epoch=epoch,
            client_offset=self.client_offset,
        )

    def prefetch_epoch(
        self, stacked_train, epoch: int, batch_size: int | None = None,
        *, k: int = 2,
    ):
        """Arm the one-slot background prefetch for ``epoch``'s first
        ``k`` lockstep batches (permutation + row gathers) — called by
        round loops right before blocking on a wire exchange, so reply
        latency hides input-pipeline work. Dense stacks only; a ragged
        (StackedClients) input is ignored (its iterator is built per
        epoch inside the ragged path). Returns the EpochPrefetcher (or
        None when ignored) so the caller can report its measured span."""
        from ..data.pipeline import StackedClients as _SC

        if isinstance(stacked_train, _SC):
            return None
        bs = self.cfg.data.batch_size if batch_size is None else int(batch_size)
        return self._prefetch.arm(
            (id(stacked_train), int(epoch), bs),
            lambda: self._epoch_iterator(stacked_train, bs, epoch),
            k=k,
        )

    def _armed_profiler(self):
        """The fit loops' shared step profiler: the one built at
        construction, or a late arm when the CLI installed the stride
        afterwards, with a fresh reporting window either way (the same
        helper shape as engine.Trainer._armed_profiler — the dense and
        packed loops must not drift). None = profiling off."""
        prof = self.step_profiler
        if prof is None:
            prof = self.step_profiler = maybe_step_profiler("train")
        if prof is not None:
            prof.begin_window()
        return prof

    def fit_local(
        self,
        state: FedState,
        stacked_train: TokenizedSplit | StackedClients,
        *,
        batch_size: int | None = None,
        epochs: int | None = None,
        epoch_offset: int = 0,
    ) -> tuple[FedState, np.ndarray]:
        """E local epochs for all clients in lockstep; returns ``[E, C]``
        per-client average losses.

        A :class:`StackedClients` input takes the ragged path: every
        client's full split trains each epoch (row-masked batches, gated
        updates); a plain :class:`TokenizedSplit` takes the dense path
        (all clients share one row count).

        Instrumented at THIS entry (not in run()): both round-loop owners
        — run() and the CLI's own loop — emit one ``client-local`` obs
        span per call, with the round derived from ``epoch_offset`` (the
        loops pass ``r * epochs_per_round``)."""
        # Arm (and window-reset) the profiler HERE, once per fit — the
        # dense and packed loops below read the armed instance, and a
        # ragged fit (unprofiled) still resets the window so its span
        # never carries a previous fit's samples.
        prof = self._armed_profiler()
        t_unix = time.time()
        t0 = time.monotonic()
        out = self._fit_local_impl(
            state,
            stacked_train,
            batch_size=batch_size,
            epochs=epochs,
            epoch_offset=epoch_offset,
        )
        self._trace_phase(
            "client-local",
            t_unix,
            time.monotonic() - t0,
            epoch_offset // max(self.cfg.train.epochs_per_round, 1),
            # Sampled step-time attribution (obs/profile.py): host vs
            # dispatch vs device p50/p95 ride the span so the timeline
            # can render the device-vs-host row. {} when profiling off.
            **(prof.span_attrs() if prof is not None else {}),
        )
        return out

    def _fit_local_impl(
        self,
        state: FedState,
        stacked_train: TokenizedSplit | StackedClients,
        *,
        batch_size: int | None = None,
        epochs: int | None = None,
        epoch_offset: int = 0,
    ) -> tuple[FedState, np.ndarray]:
        if isinstance(stacked_train, StackedClients):
            return self._fit_local_ragged(
                state,
                stacked_train,
                batch_size=batch_size,
                epochs=epochs,
                epoch_offset=epoch_offset,
            )
        bs = self.cfg.data.batch_size if batch_size is None else batch_size
        E = self.cfg.train.epochs_per_round if epochs is None else epochs
        # Hosts must execute identical train-step counts (each step is a
        # collective); bound every epoch by the global minimum batch count.
        # The zero-batch check runs AFTER the allgather so an undersized
        # host raises on every process instead of deadlocking the others
        # inside the collective.
        n_batches = stacked_train.labels.shape[1] // bs
        if self.P > 1:
            n_batches = int(self._allgather(n_batches).min())
        if n_batches == 0:
            raise ValueError(
                f"common per-client train rows ({stacked_train.labels.shape[1]}) "
                f"< batch_size ({bs}) on at least one host: zero batches per "
                "epoch. Stack with stack_clients_ragged to train tiny "
                "clients without dragging the fleet down."
            )
        if self._packed_eligible():
            return self._fit_local_packed(
                state,
                stacked_train,
                bs=bs,
                E=E,
                epoch_offset=epoch_offset,
                n_batches=n_batches,
            )
        if self.cfg.fed.prox_mu > 0.0:
            # FedProx anchor: the round-start params, copied so the donated
            # state buffers never alias it.
            anchor = jax.tree.map(jnp.copy, state.params)
            step = lambda s, b: self.train_step(s, b, anchor)  # noqa: E731
        else:
            step = self.train_step
        out = []
        telemetry = self._step_telemetry()
        prof = self.step_profiler  # armed + window-reset by fit_local
        first_memory = prof is not None
        last_loss = None  # carried ACROSS epochs: the drain fence target
        for epoch in range(epoch_offset, epoch_offset + E):
            losses = []
            batches = self._epoch_batches(stacked_train, bs, epoch)
            for batch, sampled in profiled_step_iter(
                prof, (b for _, b in zip(range(n_batches), batches))
            ):
                if sampled:
                    # Fenced sampled step (obs/profile.py): drain the
                    # async backlog, then split dispatch from device.
                    prof.drain(last_loss)
                    t_d = prof.clock()
                    state, loss = step(state, self._feed(batch))
                    prof.note_dispatch(prof.clock() - t_d)
                    prof.fence(loss)
                else:
                    state, loss = step(state, self._feed(batch))
                losses.append(loss)
                last_loss = loss
                telemetry(loss, batch["labels"].size)
                if first_memory:
                    first_memory = False
                    note_memory("post-first-step")
            epoch_avg = jnp.stack(losses).mean(axis=0) if losses else jnp.zeros(self.C)
            out.append(self._host(epoch_avg))
            for c in range(self.C):
                log.info(
                    f"Client {c} Epoch [{epoch - epoch_offset + 1}/{E}], "
                    f"Average Loss: {out[-1][c]:.4f}"
                )
        return state, np.stack(out) if out else np.zeros((0, self.C))

    @property
    def _slice_client(self):
        """Jitted per-client tree slicer (memoized on the trainer)."""
        fn = getattr(self, "_slice_client_fn", None)
        if fn is None:
            fn = jax.jit(
                lambda t, c: jax.tree.map(lambda x: x[c], t),
                static_argnums=1,
            )
            self._slice_client_fn = fn
        return fn

    @property
    def _unstack_fn(self):
        """Jitted, memoized stacked->per-client splitter. NOT donated:
        a stacked ``[C, ...]`` input buffer can never alias its per-client
        output slices (each is 1/C the bytes), so a declared donation is
        structurally unusable — XLA copies anyway and warns "Some donated
        buffers were not usable" on every packed bench/fit (VERDICT r5
        weak #2). The eager-free contract the donation was buying (the
        packed fit must not pin the stacked originals alongside the
        per-client copies; Python references in caller frames keep the
        FedState alive) is enforced in :meth:`_unstack_cstates` by
        explicitly deleting the stacked buffers after the split — same
        invalidation semantics the donation had, zero warnings."""
        fn = getattr(self, "_unstack_fn_cache", None)
        if fn is None:
            C = self.C

            def unstack(params, opt_state):
                return (
                    [jax.tree.map(lambda x: x[c], params) for c in range(C)],
                    [
                        jax.tree.map(lambda x: x[c], opt_state)
                        for c in range(C)
                    ],
                )

            fn = jax.jit(unstack)
            self._unstack_fn_cache = fn
        return fn

    @property
    def _restack_fn(self):
        """Jitted, memoized per-client->stacked assembler (a fresh jit
        per fit would re-trace the full params+opt stacking program every
        round)."""
        fn = getattr(self, "_restack_fn_cache", None)
        if fn is None:
            fn = jax.jit(
                lambda *ts: jax.tree.map(lambda *xs: jnp.stack(xs), *ts),
                out_shardings=self.sh.client,
            )
            self._restack_fn_cache = fn
        return fn

    def _unstack_cstates(self, state: FedState) -> list:
        """FedState -> per-client ``(params, opt_state, step, rng)``
        tuples for the packed step. CONSUMES the stacked params/opt
        buffers (explicit delete after the split — see :attr:`_unstack_fn`
        for why this is a delete, not a donation). Every leaf is this
        client's OWN fresh buffer — the packed step donates its cstate,
        so a buffer shared across clients (state.step) would be dead by
        client 1's first dispatch. Shared by the fit loop and bench.py's
        product-step timer."""
        pcs, ocs = self._unstack_fn(state.params, state.opt_state)
        for leaf in jax.tree.leaves((state.params, state.opt_state)):
            if isinstance(leaf, jax.Array):
                leaf.delete()
        return [
            (
                pcs[c],
                ocs[c],
                jnp.copy(state.step),
                jnp.copy(state.rngs[c]),
            )
            for c in range(self.C)
        ]

    def _packed_eligible(self) -> bool:
        """The client-packing fast path applies when every logical client
        lives on ONE device (single-process, single-device mesh — logical
        replicas packed per row): there the stacked vmapped step's
        batched-weight GEMMs run ~42% MFU vs ~57% for the identical math
        dispatched as independent per-client steps (PARITY.md r5
        decomposition). Multi-device meshes shard the clients axis and
        keep the SPMD stacked program."""
        return (
            self.P == 1
            and self.mesh.devices.size == 1
            and self._build_packed_step is not None
        )

    def _fit_local_packed(
        self,
        state: FedState,
        stacked_train: TokenizedSplit,
        *,
        bs: int,
        E: int,
        epoch_offset: int,
        n_batches: int,
    ) -> tuple[FedState, np.ndarray]:
        """Dense lockstep epochs on the client-packing fast path: unstack
        the FedState once, advance each client through its OWN jitted
        engine-style step (unbatched GEMMs, donated buffers), restack
        once at the end. Per-client rng folds and the lockstep counter
        match the vmapped step exactly
        (test_federated.py::test_packed_fit_matches_vmapped)."""
        if self._packed_step is None:
            self._packed_step = self._build_packed_step()
        step_fn = self._packed_step
        C = self.C
        mu = self.cfg.fed.prox_mu
        slice_c = self._slice_client
        # FedProx anchors: fresh round-start slices, taken BEFORE the
        # unstack below donates (consumes) the stacked params.
        anchors = (
            [slice_c(state.params, c) for c in range(C)] if mu > 0.0 else None
        )
        cstates = self._unstack_cstates(state)
        out = []
        telemetry = self._step_telemetry()
        prof = self.step_profiler  # armed + window-reset by fit_local
        first_memory = prof is not None
        last_loss = None  # carried ACROSS epochs: the drain fence target
        for epoch in range(epoch_offset, epoch_offset + E):
            losses = []
            batches = self._epoch_batches(stacked_train, bs, epoch)
            for batch, sampled in profiled_step_iter(
                prof, (b for _, b in zip(range(n_batches), batches))
            ):
                # A "step" here is one full lockstep batch: C per-client
                # dispatches. A sampled one fences the previous batch's
                # losses first, then splits dispatch from device.
                if sampled:
                    prof.drain(last_loss)
                    t_d = prof.clock()
                per = []
                for c in range(C):
                    cb = {k: v[c] for k, v in batch.items()}
                    if anchors is not None:
                        cstates[c], task = step_fn(
                            cstates[c], cb, anchors[c]
                        )
                    else:
                        cstates[c], task = step_fn(cstates[c], cb)
                    per.append(task)
                loss_vec = jnp.stack(per)
                if sampled:
                    prof.note_dispatch(prof.clock() - t_d)
                    prof.fence(loss_vec)
                losses.append(loss_vec)
                last_loss = loss_vec
                telemetry(loss_vec, batch["labels"].size)
                if first_memory:
                    first_memory = False
                    note_memory("post-first-step")
            epoch_avg = (
                jnp.stack(losses).mean(axis=0) if losses else jnp.zeros(C)
            )
            out.append(self._host(epoch_avg))
            for c in range(C):
                log.info(
                    f"Client {c} Epoch [{epoch - epoch_offset + 1}/{E}], "
                    f"Average Loss: {out[-1][c]:.4f}"
                )
        restack = self._restack_fn
        state = state._replace(
            params=restack(*[cs[0] for cs in cstates]),
            opt_state=restack(*[cs[1] for cs in cstates]),
            step=cstates[0][2],
        )
        return state, np.stack(out) if out else np.zeros((0, C))

    def _fit_local_ragged(
        self,
        state: FedState,
        stacked_train: StackedClients,
        *,
        batch_size: int | None = None,
        epochs: int | None = None,
        epoch_offset: int = 0,
    ) -> tuple[FedState, np.ndarray]:
        """Ragged lockstep epochs: the per-epoch step count is the fleet
        MAX batch count (ceil — the final short batch trains too), clients
        that exhaust their rows idle behind valid==0 masks, and reported
        per-client epoch losses average over each client's own real
        batches — the numbers an independent per-client run would log."""
        bs = self.cfg.data.batch_size if batch_size is None else batch_size
        E = self.cfg.train.epochs_per_round if epochs is None else epochs
        n_batches = max(
            (-(-int(n) // bs) for n in stacked_train.n_rows), default=0
        )
        if self.P > 1:
            # Every host runs the GLOBAL max step count (each step is a
            # collective); short hosts contribute all-masked batches.
            n_batches = int(self._allgather(n_batches).max())
        if n_batches == 0:
            raise ValueError(
                "every client's train split is empty: nothing to fit"
            )
        if self._ragged_train_step is None:
            self._ragged_train_step = self._build_ragged_step()
        if self.cfg.fed.prox_mu > 0.0:
            anchor = jax.tree.map(jnp.copy, state.params)
            step = lambda s, b: self._ragged_train_step(s, b, anchor)  # noqa: E731
        else:
            step = self._ragged_train_step
        out = []
        telemetry = self._step_telemetry()
        for epoch in range(epoch_offset, epoch_offset + E):
            losses, had = [], []
            batches = federated_batches_ragged(
                stacked_train,
                bs,
                seed=self.cfg.train.seed,
                epoch=epoch,
                client_offset=self.client_offset,
                n_batches=n_batches,
            )
            for batch in batches:
                state, (loss, has) = step(state, self._feed(batch))
                losses.append(loss)
                had.append(has)
                # Mean over ACTIVE clients only — idle clients' masked loss
                # of 0 must not understate the fleet mean.
                telemetry(loss, int(batch["valid"].sum()), active=has)
            # Per-client mean over ITS OWN batches: masked-off lockstep
            # steps carry loss 0 and has 0, so they vanish from both sums.
            total = jnp.stack(losses).sum(axis=0)
            count = jnp.stack(had).sum(axis=0)
            epoch_avg = total / jnp.maximum(count, 1.0)
            out.append(self._host(epoch_avg))
            for c in range(self.C):
                log.info(
                    f"Client {c} Epoch [{epoch - epoch_offset + 1}/{E}], "
                    f"Average Loss: {out[-1][c]:.4f}"
                )
        return state, np.stack(out) if out else np.zeros((0, self.C))

    def prepare_eval(
        self,
        splits: Sequence[TokenizedSplit],
        *,
        batch_size: int | None = None,
        target_rows: int | None = None,
    ) -> "PreparedEval":
        """Pad/stack eval splits once; reuse across rounds (re-stacking every
        evaluation would repeat the host-side concat of the full eval set).
        Multi-host callers pass only their LOCAL clients' splits plus the
        global max split length as ``target_rows``."""
        bs = self.cfg.data.eval_batch_size if batch_size is None else batch_size
        if target_rows is None and self.P > 1:
            # Hosts must agree on M (the eval loop is a sequence of
            # collectives); default to the global max split length.
            target_rows = int(
                self._allgather(max(len(s) for s in splits)).max()
            )
        stacked, valid = stack_eval_splits(
            splits, bs, pad_id=self.pad_id, target_rows=target_rows
        )
        return PreparedEval(stacked, valid, bs)

    def _step_telemetry(self):
        """Shared per-step logging closure (engine.make_step_telemetry)
        with the fleet-mean loss label. ``telemetry_prefix`` overrides the
        default tag — the C=1 TCP client adapter sets its ``[CLIENT n]``
        prefix there so mixed-fleet step logs stay attributable."""
        from ..train.engine import make_step_telemetry

        return make_step_telemetry(
            self.cfg.train.log_every,
            prefix=getattr(self, "telemetry_prefix", "[FED] "),
            label="mean loss",
        )

    @staticmethod
    def _allgather(value: int) -> np.ndarray:
        from ..parallel.multihost import allgather_hosts

        return allgather_hosts(value)

    # ------------------------------------------------------- observability
    def _trace_attrs(self) -> dict:
        """Span attributes identifying this trainer's product path (the
        3-axis fedseq subclass overrides with its seq layout)."""
        return {"path": "fed2", "clients": self.C}

    def _obs_tracer(self):
        from ..obs.trace import get_global_tracer

        return self.tracer if self.tracer is not None else get_global_tracer()

    def _trace_phase(
        self,
        name: str,
        t_start: float,
        dur_s: float,
        round_index: int,
        **extra: Any,
    ) -> None:
        tracer = self._obs_tracer()
        if tracer is not None:
            tracer.record(
                name,
                t_start=t_start,
                dur_s=dur_s,
                round=round_index,
                **self._trace_attrs(),
                **extra,
            )

    def evaluate_clients(
        self,
        stacked_params: Any,
        splits: Sequence[TokenizedSplit] | None = None,
        *,
        prepared: "PreparedEval | None" = None,
        batch_size: int | None = None,
        collect_probs: bool = False,
    ) -> list[dict]:
        """Per-client metrics dicts (reference five-metric schema)."""
        if prepared is None:
            if splits is None:
                raise ValueError("pass either splits or prepared")
            prepared = self.prepare_eval(splits, batch_size=batch_size)
        elif splits is not None or batch_size is not None:
            raise ValueError(
                "prepared already fixes the eval data and batch size; "
                "do not also pass splits/batch_size"
            )
        return evaluate_stacked(
            self, stacked_params, prepared, collect_probs=collect_probs
        )

    def participation_mask(self, round_index: int) -> np.ndarray | None:
        """Per-round participant sampling (FedConfig.participation < 1):
        a seeded 0/1 mask over clients, identical on every host. None when
        everyone participates (the reference's behavior).

        Two samplers (FedConfig.participation_mode): "fixed" draws exactly
        ``cohort_size()`` clients without replacement; "poisson" draws
        each client independently with probability ``participation`` —
        the sampler the DP accountant's subsampled-Gaussian bound assumes,
        making the reported epsilon exact (the default whenever DP is on).
        A Poisson cohort may be empty; ``run`` treats such a round as a
        no-op instead of failing (skipping on this data-INDEPENDENT event
        does not weaken the accountant's bound — both branches are
        identically distributed under adjacent datasets)."""
        if self.cfg.fed.participation >= 1.0:
            return None
        rng = np.random.default_rng(self.cfg.train.seed * 7919 + round_index)
        if self.cfg.fed.resolve_participation_mode() == "poisson":
            return (
                rng.random(self.C) < self.cfg.fed.participation
            ).astype(np.float64)
        # FedConfig.cohort_size is the single source of truth for k — the
        # DP accountant derives its effective sampling rate from the same
        # number (ceil keeps the sampled round above min_client_fraction).
        k = self.cfg.fed.cohort_size()
        mask = np.zeros(self.C, np.float64)
        mask[rng.choice(self.C, size=k, replace=False)] = 1.0
        return mask

    def round_aggregate(
        self,
        state: FedState,
        *,
        round_index: int,
        weights: np.ndarray | None = None,
        base_mask: np.ndarray | None = None,
        faults: np.ndarray | None = None,
        anchor: Any | None = None,
    ) -> FedState:
        """One round's participation sampling + gating + aggregation,
        shared by :meth:`run` and the CLI round loop.

        min_client_fraction gates CRASHED/empty clients (``base_mask``
        and ``faults``) — never the Poisson draw: a small (even empty)
        Poisson cohort is a legitimate sample the DP accountant's bound
        already covers, and gating on it would condition the sampler and
        un-exact the reported epsilon. An empty Poisson round is a no-op
        (skipping on this data-INDEPENDENT event costs no privacy — both
        branches are identically distributed under adjacent datasets)."""
        from .fedsteps import check_survivors

        mask = self.participation_mask(round_index)
        poisson = (
            mask is not None
            and self.cfg.fed.resolve_participation_mode() == "poisson"
        )
        gate = base_mask
        if base_mask is not None:
            mask = base_mask if mask is None else mask * base_mask
        # The no-op branch keys on the draw gated by the STRUCTURAL
        # base_mask (the product just computed): clients with empty
        # shards (ragged fleets) never participate, which is a fixed,
        # data-independent property — a draw landing only on them is the
        # same benign sampling event as an empty draw. A non-empty
        # effective draw whose every member then CRASHED (faults, below)
        # is a fault event and must abort loudly (same as the fixed
        # sampler), not read as a benign sampler outcome.
        draw_empty = poisson and float(mask.sum()) == 0.0
        if faults is not None:
            faults = np.asarray(faults, np.float64)
            mask = faults if mask is None else mask * faults
            gate = faults if gate is None else gate * faults
        if poisson and gate is not None:
            check_survivors(
                float(gate.sum()), self.C, self.cfg.fed.min_client_fraction
            )
        if draw_empty:
            log.info(
                f"[FED] round {round_index + 1}: empty effective Poisson "
                "cohort (no sampled client holds data) — aggregation "
                "skipped (no-op round; the DP accountant already covers "
                "this branch)"
            )
            return state
        t_unix = time.time()
        t0 = time.monotonic()
        state = self.aggregate(
            state,
            weights=weights,
            client_mask=mask,
            anchor=anchor,
            round_index=round_index,
            enforce_min_fraction=not poisson,
        )
        self._trace_phase("agg", t_unix, time.monotonic() - t0, round_index)
        # Memory watermark at the round's aggregation boundary
        # (obs/profile.py — graceful no-op on stats-less backends).
        note_memory("post-aggregate")
        return state

    def round_anchor(self, state: FedState) -> Any | None:
        """Round-start params snapshot for DP and/or FedOpt aggregation —
        capture BEFORE ``fit_local`` (a copy, so donated train-step buffers
        never alias it). None when neither needs it."""
        if self.dp_fedavg_step is None and self.server_agg_step is None:
            return None
        return jax.tree.map(jnp.copy, state.params)

    def _dp_key(self, round_index: int) -> jax.Array:
        """Per-round noise key from the run's private DP seed (fresh OS
        entropy unless FedConfig.dp_seed pins it for tests)."""
        base = jax.random.key(self._dp_seed, impl=self.cfg.train.prng_impl)
        return jax.random.fold_in(base, round_index)

    def aggregate(
        self,
        state: FedState,
        *,
        weights: np.ndarray | None = None,
        client_mask: np.ndarray | None = None,
        anchor: Any | None = None,
        round_index: int = 0,
        enforce_min_fraction: bool = True,
    ) -> FedState:
        """The FedAvg round boundary — dispatch in fedsteps.aggregate_round
        (plain/weighted/masked FedAvg, DP-FedAvg, FedOpt).
        ``enforce_min_fraction=False``: the Poisson-sampled path, where the
        run loop gates faults itself and a small cohort is legitimate."""
        return aggregate_round(
            self,
            state,
            weights=weights,
            client_mask=client_mask,
            anchor=anchor,
            round_index=round_index,
            enforce_min_fraction=enforce_min_fraction,
        )

    # ------------------------------------------------------------------- run
    def run(
        self,
        state: FedState,
        stacked_train: TokenizedSplit | StackedClients,
        eval_splits: Sequence[TokenizedSplit],
        *,
        rounds: int | None = None,
        weights: np.ndarray | None = None,
        fault_mask_fn: Callable[[int], np.ndarray | None] | None = None,
    ) -> tuple[FedState, list[RoundRecord]]:
        """The full federated flow, per round: local epochs -> local eval ->
        FedAvg -> aggregated eval (the reference's one-shot flow,
        client1.py:379-404, looped).

        ``fault_mask_fn(round) -> [C] 0/1 mask | None`` injects deterministic
        client failures for a round (a dropped client is excluded from the
        masked mean, exactly as a crashed client would be — the reference
        instead hangs its accept loop, server.py:69-71,124-132). Composes
        with partial participation: a client aggregates only if both masks
        keep it. ``min_client_fraction`` still gates the round.
        """
        R = self.cfg.fed.rounds if rounds is None else rounds
        E = self.cfg.train.epochs_per_round
        if weights is None and self.cfg.fed.resolve_weighted():
            if isinstance(stacked_train, StackedClients):
                if self.P > 1:
                    # The local ragged stack covers only this process's
                    # clients; silently falling back to a uniform mean here
                    # would make the same config aggregate differently on
                    # 1 vs N hosts. The caller must supply the GLOBAL
                    # n_train weights (cmd_federated does).
                    raise ValueError(
                        "multi-host run() cannot derive global sample-count "
                        "weights from the process-local ragged stack — pass "
                        "weights=[global n_train per client], or set "
                        "fed.weighted=False for the uniform mean"
                    )
                # The ragged stack carries true per-client sample counts —
                # the auto (weighted=None) default weights by them.
                weights = np.asarray(stacked_train.n_rows, np.float64)
            elif self.cfg.fed.weighted:
                # Explicit weighted=True without recoverable counts: the
                # fleet-min-truncated dense stack loses them — the caller
                # must supply the true n_train weights.
                raise ValueError(
                    "fed.weighted=True requires explicit per-client weights "
                    "(pass weights=[n_train per client])"
                )
        # Under a uniform mean (explicit weighted=False, or DP's forced
        # uniform), a zero-row client would average its never-trained
        # round-start params into the aggregate with full 1/C weight every
        # round; mask it out as a permanently dropped client instead (it
        # still receives the aggregate — the masked mean's output is
        # broadcast to every row). min_client_fraction applies as usual.
        base_mask: np.ndarray | None = None
        if weights is None and isinstance(stacked_train, StackedClients):
            local_empty = (np.asarray(stacked_train.n_rows) == 0).astype(np.int64)
            if self.P == 1:
                empty = local_empty > 0
            else:
                # Every host must apply the SAME mask (the aggregate is one
                # collective); clients lay process-major over the mesh, so
                # the allgather's flattened order IS the global client order.
                from jax.experimental import multihost_utils

                empty = (
                    np.asarray(
                        multihost_utils.process_allgather(local_empty)
                    ).reshape(-1)
                    > 0
                )
            if empty.any():
                base_mask = (~empty).astype(np.float64)
                log.warning(
                    f"[FED] clients {np.flatnonzero(empty).tolist()} have "
                    "zero train rows; excluding them from the uniform mean"
                )
        history: list[RoundRecord] = []
        prepared = self.prepare_eval(eval_splits)
        for r in range(R):
            anchor = self.round_anchor(state)
            with phase(f"round {r + 1}/{R} local training", tag="FED"):
                state, losses = self.fit_local(
                    state, stacked_train, epoch_offset=r * E
                )
            local = self.evaluate_clients(state.params, prepared=prepared)
            faults = None
            if fault_mask_fn is not None:
                faults = fault_mask_fn(r)
                if faults is not None:
                    faults = np.asarray(faults, np.float64)
                    dropped = [c for c in range(self.C) if faults[c] == 0]
                    if dropped:
                        log.info(
                            f"[FED] round {r + 1}: injected faults drop "
                            f"clients {dropped}"
                        )
            with phase(f"round {r + 1}/{R} FedAvg", tag="FED"):
                state = self.round_aggregate(
                    state,
                    round_index=r,
                    weights=weights,
                    base_mask=base_mask,
                    faults=faults,
                    anchor=anchor,
                )
            aggregated = self.evaluate_clients(state.params, prepared=prepared)
            history.append(RoundRecord(r, losses, local, aggregated))
            for c in range(self.C):
                log.info(
                    f"Round {r + 1} client {c}: local acc "
                    f"{local[c]['Accuracy']:.4f} -> aggregated "
                    f"{aggregated[c]['Accuracy']:.4f}"
                )
            if r + 1 < R and self.cfg.fed.reset_optimizer_each_round:
                state = self.reset_optimizer(state)
        return state, history
