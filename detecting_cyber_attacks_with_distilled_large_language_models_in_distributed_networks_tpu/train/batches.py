"""Per-epoch federated batch iterators (host side).

The reference shuffles each client's rows independently inside
``DataLoader(shuffle=True)`` (client1.py:368-372); here every client's
permutation is derived from (seed, epoch, global client index) so the
stacked ``[C, B, ...]`` lockstep batches are deterministic, epoch-decorrelated,
and identical no matter how clients are laid out over hosts.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Iterator

import numpy as np

from ..data.pipeline import StackedClients


class EpochPrefetcher:
    """Background materialization of an epoch's first K batches.

    The TCP client round loop is serial: train -> upload -> WAIT for the
    aggregate reply -> train again. The wait is dead time; this object
    spends it on the NEXT round's input pipeline instead — the per-epoch
    permutation plus the first K batches' row gathers run on a background
    thread, so when training resumes its first steps dispatch without
    touching the input pipeline. Determinism is untouched: the factory
    builds the exact iterator the epoch loop would have built (same seed,
    same epoch key), this object merely evaluates its head early.

    ``batches()`` joins the thread and yields the prefetched head, then
    drains the live iterator — byte-identical to iterating the factory's
    iterator directly (pinned by tests)."""

    def __init__(
        self,
        factory: Callable[[], Iterator[Any]],
        *,
        k: int = 2,
    ):
        self._buf: list[Any] = []
        self._it: Iterator[Any] | None = None
        self._err: BaseException | None = None
        self._k = max(0, int(k))
        self._factory = factory
        # Span accounting (the TCP client's ``batch-prefetch`` obs span):
        # when the background work started (unix) and how long it ran —
        # the input-pipeline time hidden behind the reply wait.
        self.t_unix = 0.0
        self.busy_s = 0.0
        self.n_prefetched = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        import time

        self.t_unix = time.time()
        t0 = time.monotonic()
        try:
            it = self._factory()
            for _ in range(self._k):
                try:
                    self._buf.append(next(it))
                except StopIteration:
                    it = iter(())
                    break
            self._it = it
            self.n_prefetched = len(self._buf)
        except BaseException as e:  # surface on consume, not on a daemon
            self._err = e
        finally:
            self.busy_s = time.monotonic() - t0

    def ready(self) -> bool:
        return not self._thread.is_alive()

    def batches(self) -> Iterator[Any]:
        self._thread.join()
        if self._err is not None:
            raise self._err
        yield from self._buf
        if self._it is not None:
            yield from self._it


class PrefetchSlot:
    """One-slot arm/consume pairing of an :class:`EpochPrefetcher` with
    the identity key of the epoch it was built for — the single
    implementation of the keying/drop semantics every trainer's round
    loop shares (engine.Trainer and FederatedTrainer hold one each).

    ``arm`` starts the background prefetch and remembers its key;
    ``consume`` is one-shot either way: a mismatched key (different
    split / epoch / batch size) means the armed buffer will never be
    consumed — drop it rather than pin its batches until the next arm,
    and let the caller fall back to its live iterator."""

    def __init__(self) -> None:
        self._armed: tuple[tuple, EpochPrefetcher] | None = None

    @property
    def armed(self) -> bool:
        return self._armed is not None

    def arm(
        self,
        key: tuple,
        factory: Callable[[], Iterator[Any]],
        *,
        k: int = 2,
    ) -> EpochPrefetcher:
        pf = EpochPrefetcher(factory, k=k)
        self._armed = (tuple(key), pf)
        return pf

    def consume(self, key: tuple) -> Iterator[Any] | None:
        """The armed prefetcher's ``batches()`` when ``key`` matches the
        armed epoch, else None (caller builds its live iterator)."""
        if self._armed is None:
            return None
        armed_key, pf = self._armed
        self._armed = None
        if armed_key == tuple(key):
            return pf.batches()
        return None


def federated_batches(
    stacked,
    batch_size: int,
    *,
    seed: int,
    epoch: int,
    client_offset: int = 0,
) -> Iterator[dict[str, np.ndarray]]:
    """Yields ``[C, B, ...]`` batches with every client's rows permuted
    independently per epoch (dense path: all clients share one row count,
    the fleet-min truncation applied upstream).

    ``client_offset``: this process's first GLOBAL client index — multi-host
    runs must key client c's permutation on its global identity, or two
    hosts' "client 0" would shuffle identically.
    """
    C, N = stacked.labels.shape[:2]
    perms = np.stack(
        [
            np.random.default_rng(
                (seed * 100_003 + epoch) * 1_000_003 + client_offset + c
            ).permutation(N)
            for c in range(C)
        ]
    )
    rows = np.arange(C)[:, None]
    for i in range(N // batch_size):
        idx = perms[:, i * batch_size : (i + 1) * batch_size]
        yield {
            "input_ids": stacked.input_ids[rows, idx],
            "attention_mask": stacked.attention_mask[rows, idx],
            "labels": stacked.labels[rows, idx],
        }


def federated_batches_ragged(
    stacked: StackedClients,
    batch_size: int,
    *,
    seed: int,
    epoch: int,
    client_offset: int = 0,
    n_batches: int | None = None,
) -> Iterator[dict[str, np.ndarray]]:
    """Per-epoch ``[C, B, ...]`` batches over a RAGGED client stack, with a
    ``valid`` ``[C, B]`` 0/1 mask. Each client's real rows are permuted
    independently (same keying as :func:`federated_batches`) and consumed
    exactly once per epoch: a client whose rows run out pads its remaining
    lockstep batches with valid == 0 (its train step is gated off), and the
    final partial batch mixes real and padding rows. ``n_batches`` lets
    multi-host callers force the GLOBAL max step count.

    Every batch also carries ``warmup_step`` ``[C, B]`` — each client's OWN
    executed-step count entering this batch (``epoch * ceil(n_c/bs) +
    min(i, ceil(n_c/bs))``, broadcast over B so it rides the standard batch
    sharding). The ragged train step keys LR warmup on it, so a short
    client's schedule advances only when the client actually steps —
    matching its independent-run trajectory (the dense path's global
    ``state.step`` would compress idle clients' warmup ramps)."""
    C = stacked.split.labels.shape[0]
    own_steps = np.array(
        [-(-int(n) // batch_size) for n in stacked.n_rows], np.int32
    )
    min_steps = int(own_steps.max())
    steps = n_batches
    if steps is None:
        steps = min_steps
    elif steps < min_steps:
        worst = int(own_steps.argmax())
        raise ValueError(
            f"n_batches={steps} is smaller than client {worst}'s own epoch "
            f"length ceil({int(stacked.n_rows[worst])}/{batch_size})="
            f"{min_steps}; every client's rows must fit the lockstep span"
        )
    span = steps * batch_size
    idx = np.zeros((C, span), np.int64)
    valid = np.zeros((C, span), np.int32)
    for c in range(C):
        n_c = int(stacked.n_rows[c])
        perm = np.random.default_rng(
            (seed * 100_003 + epoch) * 1_000_003 + client_offset + c
        ).permutation(n_c)
        idx[c, :n_c] = perm
        valid[c, :n_c] = 1
    rows = np.arange(C)[:, None]
    for i in range(steps):
        sl = slice(i * batch_size, (i + 1) * batch_size)
        take = idx[:, sl]
        wstep = epoch * own_steps + np.minimum(i, own_steps)
        yield {
            "input_ids": stacked.split.input_ids[rows, take],
            "attention_mask": stacked.split.attention_mask[rows, take],
            "labels": stacked.split.labels[rows, take],
            "valid": valid[:, sl],
            "warmup_step": np.broadcast_to(
                wstep[:, None], (C, batch_size)
            ).copy(),
        }
