"""Meshed local training for the separate-process TCP client.

The reference's real deployment shape is independent client processes
talking TCP to an aggregation server (reference client1.py:276-336); until
this module, our client on that tier trained its local phase on ONE device
no matter how many chips its host had. ``fedtpu client --data-parallel N
[--seq-parallel M]`` drives the local phase over the host's own device
mesh instead, reusing the existing meshed machinery:

* ``--data-parallel N`` alone -> :class:`MeshTrainer`: the single-client
  engine's OWN jitted programs (train/engine.py), dispatched with batch
  rows sharded over a per-host ``data`` mesh axis and params replicated —
  XLA inserts the gradient psum. Same math, same PRNG streams, same
  shuffles: the trajectory is threefry-identical to the single-device
  client (params agree to float32 reduction-order ulps — the per-shard
  partial sums round differently than one sequential reduction — which is
  below every metric's resolution).
* ``--seq-parallel M`` (with or without data shards) ->
  :class:`FedSeqClientTrainer`: a C=1 FedSeqTrainer over a local
  ``1 x data x seq`` mesh — ring attention over the sequence axis, the
  long-context composition (parallel/fedseq.py) behind the single-client
  surface the TCP round loop drives.

Both trainers keep the wire tier untouched: params gather to host as one
replica readback for the upload, and a received aggregate is scattered
straight onto the mesh by ``init_state`` (``adopt_aggregate``) — no
intermediate full-replica state on the host beyond the wire buffer
itself. Secure aggregation and central DP therefore compose unchanged:
masking and noising operate on the host-gathered flat vector exactly as
for the single-device client.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config import ExperimentConfig, ModelConfig, TrainConfig
from ..data.pipeline import TokenizedSplit, shard_rows, stack_clients
from ..parallel.mesh import (
    device_tree_bytes,
    fsdp_sharding,
    fsdp_tree_shardings,
    make_host_mesh,
)
from ..utils.logging import get_logger
from .engine import (
    Trainer,
    TrainState,
    make_fsdp_eval_step,
    make_fsdp_train_step,
)

log = get_logger()


class MeshTrainer(Trainer):
    """The single-client engine over a per-host ``data`` mesh axis.

    Reuses the engine's cached jitted programs verbatim; only placement
    changes — batch rows shard over ``data``, state replicates. A batch
    whose row count doesn't divide the axis (the final short batch under
    ``drop_remainder=False``) is placed replicated, keeping the math (and
    so the trajectory) identical to the single-device engine.
    """

    def __init__(
        self,
        model_cfg: ModelConfig,
        train_cfg: TrainConfig,
        *,
        mesh,
        pad_id: int = 0,
        drop_remainder: bool = True,
    ):
        super().__init__(
            model_cfg, train_cfg, pad_id=pad_id, drop_remainder=drop_remainder
        )
        self.mesh = mesh
        self.batch_sharding = NamedSharding(mesh, P("data"))
        self.replicated = NamedSharding(mesh, P())
        self._install_steps(
            self.train_step,
            self.eval_step,
            lambda p: jax.device_put(p, self.replicated),
        )

    def _install_steps(self, base_train, base_eval, place_params) -> None:
        """Wrap base jitted steps with the mesh tier's batch placement
        (rows over ``data``; a short batch that doesn't divide goes
        replicated, keeping the math identical) and ``place_params`` for
        the eval path — the ONE wrapper shape shared by the replicated
        and FSDP trainers, so batch-placement fixes can't drift apart."""

        def train_step(state, batch, *extra):
            # *extra: the FedProx anchor when TrainConfig.prox_mu > 0 —
            # it is already placed (a copy of live params, so it carries
            # their sharding); only the batch needs row placement.
            return base_train(
                state,
                shard_rows(batch, self.batch_sharding, self.replicated),
                *extra,
            )

        def eval_step(params, batch, valid):
            placed = shard_rows(
                {**batch, "valid": valid},
                self.batch_sharding,
                self.replicated,
            )
            return base_eval(
                params=place_params(params),
                batch={k: v for k, v in placed.items() if k != "valid"},
                valid=placed["valid"],
            )

        self.train_step = train_step
        self.eval_step = eval_step

    def init_state(
        self, seed: int | None = None, params: Any | None = None
    ) -> TrainState:
        """Build the engine state, then scatter it onto the mesh
        (replicated) — also the aggregate-adoption path, so a received
        round reply lands on every local device in one placement."""
        state = super().init_state(seed=seed, params=params)
        return jax.device_put(state, self.replicated)

    def evaluate(self, params: Any, split, **kw: Any) -> dict:
        """Place host params on the mesh ONCE before the batch sweep (the
        per-batch wrapper's device_put is then a no-op short-circuit —
        without this, a host aggregate would re-cross the device boundary
        on every eval batch)."""
        return super().evaluate(
            jax.device_put(params, self.replicated), split, **kw
        )

    def reply_leaf_sink(self, key: str, arr: np.ndarray) -> Any:
        """Streamed-reply leaf placement (comm/client.py
        ``reply_leaf_sink``): scatter one decoded aggregate leaf onto the
        local mesh (replicated) the moment its chunk bytes land, so the
        host->device transfer of leaf k overlaps the wire transfer of
        leaf k+1 and ``adopt_aggregate`` starts from device-backed
        buffers instead of a full host-side tree. ``init_state``'s
        later device_put of an already-placed leaf is a no-op, and the
        values are bit-identical to the host-tree path (placement only,
        no arithmetic)."""
        return jax.device_put(arr, self.replicated)


class FsdpMeshTrainer(MeshTrainer):
    """FSDP shard-at-rest over the per-host ``data`` mesh axis
    (``client --data-parallel N --fsdp``).

    :class:`MeshTrainer` buys batch throughput but replicates params AND
    Adam moments on every chip — the multi-chip tier stays memory-bound
    at the single-chip model ceiling. Here the static state shards at
    rest (per-leaf specs from ``parallel/mesh.fsdp_spec``: the largest
    axis-divisible dimension of each leaf over ``data``; undividable
    leaves replicate) and the jitted train step all-gathers params AT
    USE inside a remat region tagged so the backward RE-GATHERS instead
    of retaining full-size weights; gradients reduce-scatter back onto
    the shards and Adam updates run shard-local. Per-chip static bytes
    scale ~1/N (bench-asserted, ``fsdp_peak_param_opt_bytes_ratio``).

    Contracts carried over from the replicated mesh:

    * trajectory: same threefry PRNG streams, same shuffles, same update
      arithmetic — params agree with the replicated/single-device client
      to fp32 reduction-order ulps (reduce-scatter may sum grad partials
      in a different order than the all-reduce; allclose-pinned, the
      PR-2/PR-7 documented class), metrics equal.
    * wire tier untouched: ``host_params`` gathers one full tree at the
      exchange/checkpoint boundary ONLY (``comm/client.py`` keeps the
      gather lazy via ``flatten_lazy`` — leaf k+1 gathers while chunk k
      streams), ``reply_leaf_sink`` scatters each decoded reply leaf
      straight onto its shard, so secure-agg/DP/streamed uploads compose
      unchanged.
    """

    def __init__(
        self,
        model_cfg: ModelConfig,
        train_cfg: TrainConfig,
        *,
        mesh,
        pad_id: int = 0,
        drop_remainder: bool = True,
    ):
        super().__init__(
            model_cfg,
            train_cfg,
            mesh=mesh,
            pad_id=pad_id,
            drop_remainder=drop_remainder,
        )
        self.n_shards = int(mesh.shape["data"])
        # Per-trainer memo of the jitted sharded optimizer.init (see
        # _init_opt_state — adopt_aggregate hits it every round).
        self._opt_init_jit = None
        # Replace the replicated base steps MeshTrainer installed with
        # the spec-parameterized FSDP programs; the batch-placement
        # wrapper shape is shared (_install_steps), only the base steps
        # and the eval params placement differ. The programs are
        # process-wide memoized on (configs, mesh) like the engine's —
        # same-config trainers (multi-round flows, the test suite) share
        # one set of compiled executables.
        from .engine import step_key_cfg

        base_train, base_eval = _fsdp_steps(
            model_cfg, step_key_cfg(train_cfg), mesh
        )
        # Eval params placement is identity per batch: evaluate() below
        # owns the ONE host->shard placement before the batch sweep, and
        # evaluate_state feeds the live (already sharded) state — a
        # per-batch place_state_tree would rebuild the whole per-leaf
        # sharding tree on every metrics batch for a guaranteed no-op.
        self._install_steps(base_train, base_eval, lambda params: params)

    # ------------------------------------------------------------ placement
    def leaf_sharding(self, shape) -> NamedSharding:
        """The shard-at-rest placement of one leaf — shape-deterministic
        (parallel/mesh.fsdp_spec), so the wire tier can place a decoded
        reply leaf with no layout negotiation."""
        return fsdp_sharding(self.mesh, tuple(int(d) for d in shape))

    def place_state_tree(self, tree: Any) -> Any:
        """Scatter a host (or replicated) tree onto its per-leaf shards;
        a leaf already living on its shard spec is a no-op."""
        return jax.device_put(tree, fsdp_tree_shardings(tree, self.mesh))

    def init_state(
        self, seed: int | None = None, params: Any | None = None
    ) -> TrainState:
        """Engine state scattered shard-at-rest — also the
        aggregate-adoption path: a received round reply lands directly on
        its shards (leaves the streamed-reply sink already placed pass
        through untouched), and fresh Adam moments materialize SHARDED
        (zeros_like of sharded params), never full-size per chip.
        The seed/PRNG/param-init sequence is the base Trainer's (the
        trajectory contract lives in ONE place); only placement differs,
        via the _place_init_params/_init_opt_state hooks below —
        MeshTrainer's replicated placement is deliberately skipped."""
        state = Trainer.init_state(self, seed=seed, params=params)
        # params are shard-at-rest via _place_init_params and the
        # moments via the jitted init's out_shardings — one placement
        # mechanism, nothing to re-place here (step/rng are scalar/key
        # leaves the first jitted step commits).
        self._note_static_bytes(state)
        return state

    def _place_init_params(self, params: Any) -> Any:
        return self.place_state_tree(params)

    def _init_opt_state(self, params: Any) -> Any:
        # Jitted init with EXPLICIT out_shardings: zeros_like moments
        # materialize directly ON their shards — never full-size per
        # chip. Propagation from the sharded params alone is not enough
        # (measured: it replicates the moments), so the at-rest layout
        # is pinned from the eval_shape template. The wrapper is cached
        # per trainer — init_state runs on EVERY round's aggregate
        # adoption, and a fresh jax.jit per call would re-trace there.
        fn = self._opt_init_jit
        if fn is None:
            template = jax.eval_shape(self.optimizer.init, params)
            fn = self._opt_init_jit = jax.jit(
                self.optimizer.init,
                out_shardings=fsdp_tree_shardings(template, self.mesh),
            )
        return fn(params)

    def _note_static_bytes(self, state: TrainState) -> None:
        """Per-chip static-state accounting gauge
        (``fedtpu_fsdp_static_state_bytes``): exact addressable-shard
        bytes of params + optimizer state on one device — the number the
        FSDP bench's peak ratio is built from, exported so a live client
        shows its sharding actually engaged."""
        from ..obs.metrics import default_registry

        default_registry().gauge(
            "fedtpu_fsdp_static_state_bytes",
            help="per-device bytes of FSDP shard-at-rest params + "
            "optimizer state",
        ).set(
            float(
                device_tree_bytes((state.params, state.opt_state))
            )
        )

    # ----------------------------------------------------------- wire tier
    def evaluate(self, params: Any, split, **kw: Any) -> dict:
        """Place host params onto their shards ONCE before the batch
        sweep (the per-batch wrapper's placement is then a no-op).
        Skips MeshTrainer.evaluate — its replicated device_put would
        un-shard the tree (a full copy per chip, exactly what FSDP
        exists to avoid)."""
        return Trainer.evaluate(
            self, self.place_state_tree(params), split, **kw
        )

    def host_params(self, state) -> Any:
        """The wire-upload form WITHOUT an eager device->host gather:
        leaves stay device-backed on their shards, so the streamed
        upload's packer (comm/client.py: ``wire.flatten_lazy`` plans
        from shape/dtype metadata, ``_stream_upload`` np.asarray's one
        leaf at a time) gathers leaf k+1 off its shards while chunk k
        is already on the wire — at no point does a full host-side tree
        exist beyond the in-flight leaf. The dense/DP/secure paths call
        ``_host_params`` on this tree themselves (one gather per
        exchange); values are identical either way."""
        return state.params

    def reply_leaf_sink(self, key: str, arr: np.ndarray) -> Any:
        """Streamed-reply leaf placement: scatter one decoded aggregate
        leaf DIRECTLY ONTO ITS SHARD the moment its chunk bytes land —
        the FSDP twin of MeshTrainer's replicated sink, so adoption
        never materializes a full host-side tree AND never replicates a
        leaf that is about to live sharded anyway. Values bit-identical
        to the host-tree path (placement only, no arithmetic)."""
        return jax.device_put(arr, self.leaf_sharding(np.shape(arr)))


@lru_cache(maxsize=None)
def _fsdp_steps(model_cfg: ModelConfig, key_cfg: TrainConfig, mesh):
    """Process-wide memo of the FSDP jitted programs, keyed on the
    frozen configs + the mesh they are pure functions of (the caller
    canonicalizes step-irrelevant TrainConfig fields out, exactly like
    engine._engine_steps — and two ``make_host_mesh(N)`` calls over the
    same devices compare equal, so same-shape trainers share one set of
    compiled executables). gather/constrain are pure functions of the
    mesh: gather places every leaf replicated (the all-gather-at-use);
    constrain pins a tree back onto its shard-at-rest specs
    (the reduce-scatter / shard-at-rest layout)."""
    from .engine import _engine_steps

    model, optimizer, _, _ = _engine_steps(model_cfg, key_cfg)
    replicated = NamedSharding(mesh, P())

    def gather(params):
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, replicated),
            params,
        )

    def constrain(tree):
        # fsdp_tree_shardings is the ONE layout definition (dtype-guarded:
        # non-float/int leaves replicate) — the same call init_state/
        # place_state_tree place at-rest state with, so the in-step
        # constraint can never disagree with the adoption path's layout.
        # Works on tracers too (only .shape/.dtype are read).
        return jax.tree.map(
            jax.lax.with_sharding_constraint,
            tree,
            fsdp_tree_shardings(tree, mesh),
        )

    return (
        make_fsdp_train_step(
            model,
            optimizer,
            key_cfg.warmup_steps,
            prox_mu=key_cfg.prox_mu,
            gather=gather,
            constrain=constrain,
        ),
        make_fsdp_eval_step(model, gather=gather),
    )


class FedSeqClientTrainer:
    """C=1 FedSeqTrainer behind the TCP client's single-client surface.

    The sequence-parallel composition (ring attention over a ``seq`` mesh
    axis, optional batch shards over ``data``) already exists as the
    3-axis federated trainer; a fleet of one reuses it wholesale. The
    trajectory is the fedseq one (hash-keyed dropout, federated batch
    permutations) — shard-count-invariant on its own terms, but distinct
    from the single-device engine's; use plain ``--data-parallel`` when
    byte-level parity with the single-device client matters.
    """

    def __init__(self, cfg: ExperimentConfig, *, pad_id: int = 0):
        from ..parallel.fedseq import make_seq_mesh
        from .seqfed import FedSeqTrainer

        self.cfg = dataclasses.replace(
            cfg,
            fed=dataclasses.replace(cfg.fed, num_clients=1),
            mesh=dataclasses.replace(cfg.mesh, clients=1),
        )
        mesh = make_seq_mesh(
            1, cfg.mesh.data, cfg.mesh.seq, devices=jax.local_devices()
        )
        self.inner = FedSeqTrainer(self.cfg, pad_id=pad_id, mesh=mesh)
        self.mesh = mesh
        self.pad_id = pad_id
        # Single-entry caches keyed on split identity: the TCP round loop
        # feeds the SAME split objects every round, and re-stacking the
        # full train set (or re-padding the eval set, twice per round)
        # is pure wasted host memory traffic (prepare_eval's own contract
        # is pad once, reuse across rounds).
        self._train_cache: tuple[Any, Any] | None = None
        self._eval_cache: tuple[Any, int | None, Any] | None = None

    def init_state(self, seed: int | None = None, params: Any | None = None):
        return self.inner.init_state(seed=seed, params=params)

    def fit(
        self,
        state,
        split: TokenizedSplit,
        *,
        batch_size: int = 16,
        epochs: int | None = None,
        epoch_offset: int = 0,
        tag: str = "",
    ):
        """E local epochs over the dense [1, N, ...] stack; returns the
        engine-shaped per-epoch loss list. ``tag`` (the TCP round loop's
        ``[CLIENT n]`` prefix) rides the inner trainer's step telemetry so
        mixed-fleet logs stay attributable."""
        if tag:
            self.inner.telemetry_prefix = tag
        if self._train_cache is None or self._train_cache[0] is not split:
            self._train_cache = (split, stack_clients([split]))
        stacked = self._train_cache[1]
        state, losses = self.inner.fit_local(
            state,
            stacked,
            batch_size=batch_size,
            epochs=epochs,
            epoch_offset=epoch_offset,
        )
        return state, [float(e[0]) for e in losses]

    def evaluate(
        self,
        params: Any,
        split: TokenizedSplit,
        *,
        batch_size: int | None = None,
        collect_probs: bool = True,
    ) -> dict:
        """Five reference metrics for UNSTACKED params (e.g. a received
        aggregate): stack to [1, ...], run the 3-axis eval sweep."""
        from ..parallel.fedavg import stack_params

        stacked = jax.device_put(
            stack_params(jax.tree.map(np.asarray, params), 1),
            self.inner.sh.client,
        )
        return self._evaluate_stacked(
            stacked, split, batch_size=batch_size, collect_probs=collect_probs
        )

    def evaluate_state(
        self, state, split: TokenizedSplit, *, collect_probs: bool = True
    ) -> dict:
        """Metrics straight from the (already stacked) live state."""
        return self._evaluate_stacked(
            state.params, split, collect_probs=collect_probs
        )

    def _evaluate_stacked(
        self,
        stacked_params,
        split: TokenizedSplit,
        *,
        batch_size: int | None = None,
        collect_probs: bool = True,
    ) -> dict:
        # Normalize the default BEFORE keying the cache: the round loop's
        # local eval (evaluate_state, batch_size=None) and aggregated eval
        # (evaluate) must share one prepared entry, and both default to
        # the config's eval batch size.
        if batch_size is None:
            batch_size = self.inner.cfg.data.eval_batch_size
        cache = self._eval_cache
        if cache is None or cache[0] is not split or cache[1] != batch_size:
            cache = self._eval_cache = (
                split,
                batch_size,
                self.inner.prepare_eval([split], batch_size=batch_size),
            )
        return self.inner.evaluate_clients(
            stacked_params, prepared=cache[2], collect_probs=collect_probs
        )[0]

    def prefetch_epoch(
        self, split: TokenizedSplit, epoch: int, batch_size: int, *, k: int = 2
    ):
        """Arm the inner fedseq trainer's epoch prefetch for the stacked
        form of ``split`` (the same cached stack ``fit`` trains on), so
        the TCP round loop can hide reply latency behind the next round's
        first batch gathers — mirroring engine.Trainer.prefetch_epoch."""
        if self._train_cache is None or self._train_cache[0] is not split:
            self._train_cache = (split, stack_clients([split]))
        return self.inner.prefetch_epoch(
            self._train_cache[1], epoch, batch_size, k=k
        )

    def step_profile_attrs(self) -> dict:
        """The inner fedseq trainer's sampled step attrs (obs/profile.py)
        — the TCP round loop stamps them on the client-local span."""
        prof = self.inner.step_profiler
        return prof.span_attrs() if prof is not None else {}

    def host_params(self, state) -> Any:
        """One replica of the single client's params, unstacked, on host —
        the wire-upload form."""
        return jax.tree.map(lambda x: np.asarray(x)[0], state.params)

    def adopt_aggregate(self, state, aggregated: Any):
        """Fresh Adam from the received aggregate, continuing step counter
        — the shared adoption semantics (engine.py); init_state scatters
        the aggregate onto the 3-axis mesh."""
        from .engine import adopt_aggregate_with_fresh_opt

        return adopt_aggregate_with_fresh_opt(self, state, aggregated)


def make_client_trainer(
    cfg: ExperimentConfig, *, pad_id: int = 0
) -> Trainer | FedSeqClientTrainer:
    """The TCP client's local-phase trainer for the resolved mesh config:
    plain engine (1x1), data-parallel meshed engine (Nx1) — replicated or
    FSDP shard-at-rest (``--fsdp``) — or the C=1 sequence-parallel
    composition (NxM, M > 1)."""
    data, seq = cfg.mesh.data, cfg.mesh.seq
    if data > 1 and cfg.data.batch_size % data:
        # Both branches: fail at construction with an operator-readable
        # message, not mid-round with an XLA sharding traceback.
        raise ValueError(
            f"batch_size={cfg.data.batch_size} must divide over "
            f"--data-parallel {data} (row shards)"
        )
    if cfg.mesh.fsdp:
        # (MeshConfig validates fsdp needs data >= 2 and no seq axis;
        # make_host_mesh validates the local device count.)
        if cfg.train.prng_impl != "threefry2x32":
            log.warning(
                f"[CLIENT-FSDP] prng_impl={cfg.train.prng_impl!r}: dropout "
                "masks are not shard-invariant under this impl; set "
                "train.prng_impl='threefry2x32' for replicated-mesh parity"
            )
        return FsdpMeshTrainer(
            cfg.model,
            cfg.train,
            mesh=make_host_mesh(data),
            pad_id=pad_id,
            drop_remainder=cfg.data.drop_remainder,
        )
    if seq > 1:
        # (FedSeqTrainer's own __init__ validates max_len % seq and the
        # local device count, also as ValueError.)
        return FedSeqClientTrainer(cfg, pad_id=pad_id)
    if data > 1:
        if cfg.train.prng_impl != "threefry2x32":
            # rbg/unsafe_rbg bits are NOT guaranteed identical across
            # shardings of one computation (JAX PRNG docs), so dropout
            # masks — and with them the trajectory — can diverge from the
            # single-device client. Training is still correct; only the
            # strict single-device parity needs threefry.
            log.warning(
                f"[CLIENT-MESH] prng_impl={cfg.train.prng_impl!r}: dropout "
                "masks are not shard-invariant under this impl, so the "
                "--data-parallel trajectory may diverge from the "
                "single-device client's; set train.prng_impl="
                "'threefry2x32' for threefry-identical parity"
            )
        return MeshTrainer(
            cfg.model,
            cfg.train,
            mesh=make_host_mesh(data),
            pad_id=pad_id,
            drop_remainder=cfg.data.drop_remainder,
        )
    return Trainer(
        cfg.model,
        cfg.train,
        pad_id=pad_id,
        drop_remainder=cfg.data.drop_remainder,
    )
