"""Single-client train/eval engine.

Replaces the reference's per-batch Python loop (reference client1.py:96-115:
``zero_grad -> forward -> CE loss -> backward -> Adam step`` at ~2.5 batch/s
on CPU) with one jitted, donated train step: ``value_and_grad`` +
``optax.adam(2e-5)`` traced once, every batch a single device dispatch.
Evaluation (reference client1.py:118-150) becomes a jitted step accumulating
sufficient statistics on device; the five reference metrics and the confusion
matrix finalize on host from eight scalars.
"""

from __future__ import annotations

from dataclasses import replace
from functools import lru_cache, partial
from typing import Any, Callable, Iterator, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..config import ModelConfig, TrainConfig
from ..data.pipeline import TokenizedSplit, batch_iterator, pad_split_to_batch
from ..models.distilbert import DDoSClassifier, init_params
from ..obs.profile import (
    default_ledger,
    maybe_step_profiler,
    note_memory,
    profiled_step_iter,
)
from ..ops.metrics import (
    BinaryCounts,
    ClassCounts,
    binary_counts,
    class_counts,
    finalize_class_metrics,
    finalize_metrics,
)
from .batches import PrefetchSlot
from ..utils.logging import get_logger

log = get_logger()


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jnp.ndarray  # int32 scalar
    rng: jax.Array  # dropout PRNG key, folded per step


def warmup_factor(step: jnp.ndarray, warmup_steps: int) -> jnp.ndarray:
    """Linear LR warmup multiplier driven by the GLOBAL step counter.

    Scaling the optimizer's update is equivalent to scaling Adam's learning
    rate; keying on ``state.step`` (never reset) instead of an optax
    schedule count (which lives in opt_state) means per-round optimizer
    resets (FedConfig.reset_optimizer_each_round) restart the moments — the
    reference's fresh-Adam semantics — without restarting the warmup ramp.
    """
    if warmup_steps <= 0:
        return jnp.float32(1.0)
    return jnp.minimum(1.0, (step.astype(jnp.float32) + 1.0) / warmup_steps)


def apply_warmup(updates: Any, step: jnp.ndarray, warmup_steps: int) -> Any:
    """Scale an optimizer update tree by the warmup factor (no-op traced
    away at warmup_steps=0). The single shared implementation for the
    engine, federated, and distillation steps."""
    if warmup_steps <= 0:
        return updates
    w = warmup_factor(step, warmup_steps)
    return jax.tree.map(lambda u: u * w, updates)


def prox_sq(params: Any, anchor: Any) -> jnp.ndarray:
    """FedProx squared distance ``sum ||p - anchor||^2`` over a param
    pytree — the proximal term's single shared implementation for the
    dense (train/fedsteps.py) and sequence-parallel (parallel/fedseq.py)
    federated steps, so their trajectories can't silently diverge."""
    return sum(
        jnp.sum(jnp.square(a - b))
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(anchor))
    )


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    """Adam(lr=2e-5) as the reference (client1.py:380); optional grad clip
    and decoupled weight decay the reference lacks. LR warmup is applied by
    the train step (see :func:`warmup_factor`), not here."""
    tx: list[optax.GradientTransformation] = []
    if cfg.max_grad_norm is not None:
        tx.append(optax.clip_by_global_norm(cfg.max_grad_norm))
    if cfg.weight_decay > 0.0:
        tx.append(
            optax.adamw(
                cfg.learning_rate,
                b1=cfg.b1,
                b2=cfg.b2,
                eps=cfg.eps,
                weight_decay=cfg.weight_decay,
            )
        )
    else:
        tx.append(optax.adam(cfg.learning_rate, b1=cfg.b1, b2=cfg.b2, eps=cfg.eps))
    opt = optax.chain(*tx)
    if cfg.trainable == "head":
        # FedPer-style scope: zero every update outside the classifier
        # head. Labels derive from the params' top-level structure
        # ({"encoder": ..., "classifier": ...}, models/distilbert.py), so
        # the same optimizer serves the single-client engine and the
        # stacked federated steps unchanged.
        opt = optax.multi_transform(
            {"train": opt, "freeze": optax.set_to_zero()},
            param_labels=lambda params: {
                k: jax.tree.map(
                    lambda _: "train" if k == "classifier" else "freeze", v
                )
                for k, v in params.items()
            },
        )
    if cfg.grad_accum_steps > 1:
        opt = optax.MultiSteps(opt, cfg.grad_accum_steps)
    return opt


def loss_fn(model: DDoSClassifier, params, batch, rng) -> jnp.ndarray:
    logits = model.apply(
        {"params": params},
        batch["input_ids"],
        batch["attention_mask"],
        False,  # train mode: dropout active
        rngs={"dropout": rng},
    )
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, batch["labels"]
    ).mean()


def masked_loss_fn(model: DDoSClassifier, params, batch, rng) -> jnp.ndarray:
    """Training CE over the batch's ``valid`` rows only (mean over valid;
    0 for an all-padding batch). Equals :func:`loss_fn` on the valid subset
    — the ragged federated path's per-batch objective, so a padded stacked
    client optimizes exactly what an independent run on its own rows would
    (reference DataLoader semantics incl. the short final batch,
    client1.py:370 with torch's drop_last=False default)."""
    logits = model.apply(
        {"params": params},
        batch["input_ids"],
        batch["attention_mask"],
        False,
        rngs={"dropout": rng},
    )
    per_example = optax.softmax_cross_entropy_with_integer_labels(
        logits, batch["labels"]
    )
    v = batch["valid"].astype(jnp.float32)
    return (per_example * v).sum() / jnp.maximum(v.sum(), 1.0)


def eval_counts(
    model: DDoSClassifier, params, batch, valid
) -> tuple[BinaryCounts | ClassCounts, jnp.ndarray]:
    """Shared eval body: masked batch-mean loss + sufficient statistics +
    a scalar score per row. Single source of truth for both the
    single-client and the vmapped federated eval paths (their metrics must
    never diverge). The branch on the head width is STATIC (a trace-time
    Python int), so K = 2 keeps the binary kernels verbatim — bit-identical
    to the pre-K-class path — and K > 2 accumulates the [K, K] confusion
    matrix with ``P(any attack) = 1 - P(class 0)`` as the scalar score the
    serving/drift plane consumes (one [0, 1] score axis for every K)."""
    logits = model.apply(
        {"params": params}, batch["input_ids"], batch["attention_mask"], True
    )
    per_example = optax.softmax_cross_entropy_with_integer_labels(
        logits, batch["labels"]
    )
    v = valid.astype(jnp.float32)
    # Batch-mean over valid rows (reference averages per batch then over
    # batches, client1.py:135,144; padded rows must not contribute).
    loss = (per_example * v).sum() / jnp.maximum(v.sum(), 1.0)
    if int(logits.shape[-1]) == 2:
        counts = binary_counts(logits, batch["labels"], loss, valid)
        probs = jax.nn.softmax(logits, axis=-1)[:, 1]
        return counts, probs
    counts = class_counts(logits, batch["labels"], loss, valid)
    probs = 1.0 - jax.nn.softmax(logits, axis=-1)[:, 0]
    return counts, probs


def make_step_telemetry(
    log_every: int, *, prefix: str = "", label: str = "loss"
) -> Callable:
    """Per-step telemetry closure shared by the single-client and federated
    fit loops (the reference's tqdm per-batch loss/rate line,
    client1.py:101,112). Returns ``emit(loss, n_samples, active=None)``:
    every ``log_every`` calls it logs the step, the mean loss — over
    ``active`` clients only when given (idle ragged clients carry masked
    loss 0 and must not understate the fleet mean) — and samples/s since
    the previous log point. Each log point syncs the device once; between
    them losses stay device-side so async dispatch never stalls.
    ``log_every=0`` disables."""
    import time

    acc = {"steps": 0, "samples": 0, "t": time.perf_counter()}

    def emit(loss, n_samples: int, active=None) -> None:
        if not log_every:
            return
        acc["steps"] += 1
        acc["samples"] += int(n_samples)
        if acc["steps"] % log_every:
            return
        if active is None:
            mean = float(jnp.mean(loss))
        else:
            mean = float(jnp.sum(loss) / jnp.maximum(jnp.sum(active), 1.0))
        now = time.perf_counter()
        sps = acc["samples"] / max(now - acc["t"], 1e-9)
        acc["t"], acc["samples"] = now, 0
        log.info(
            f"{prefix}Step {acc['steps']}: {label} {mean:.4f} "
            f"({sps:.1f} samples/s)"
        )

    return emit


def make_train_step(
    model: DDoSClassifier,
    optimizer: optax.GradientTransformation,
    warmup_steps: int = 0,
    *,
    prox_mu: float = 0.0,
    gather: Callable | None = None,
    constrain: Callable | None = None,
    site: str = "engine.train_step",
) -> Callable[[TrainState, dict], tuple[TrainState, jnp.ndarray]]:
    """One jitted SGD step; params/opt_state buffers are donated.

    ``gather``/``constrain`` spec-parameterize the step for FSDP
    shard-at-rest state (see :func:`make_fsdp_train_step`, the named
    entry): gather runs inside a :func:`fsdp_remat_loss` region so the
    backward re-gathers; constrain reduce-scatters grads and pins the
    updated params/opt leaves back onto their shards. None/None (the
    default) is the literal replicated step — ONE update-math
    implementation, the replicated/FSDP trajectories can't drift.

    ``prox_mu > 0`` is the FedProx client step (strategies/ fedprox):
    the returned callable takes ``(state, batch, anchor)`` and adds
    ``mu/2 * ||p - anchor||^2`` (:func:`prox_sq`) to the loss — on the
    RAW (possibly shard-at-rest) params outside the remat region, so
    its gradient ``mu * (p - anchor)`` needs no gather and inherits the
    params' sharding, composing with ``--fsdp`` for free. The anchor is
    a call argument, not a closure: it changes every round and must not
    retrace."""
    ledger = default_ledger()
    note_compile = ledger.hook(site)
    if gather is not None:
        tagged = _tag_gather(gather)
        loss_rm = fsdp_remat_loss(
            lambda p, batch, step_rng: loss_fn(model, tagged(p), batch, step_rng)
        )
    else:
        def loss_rm(p, batch, step_rng):
            return loss_fn(model, p, batch, step_rng)

    def _apply_grads(state, loss, grads):
        # The ONE update tail (constrain -> optimizer -> warmup -> apply)
        # shared by the plain and prox entries — the update math cannot
        # drift between them.
        if constrain is not None:
            grads = constrain(grads)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        updates = apply_warmup(updates, state.step, warmup_steps)
        params = optax.apply_updates(state.params, updates)
        if constrain is not None:
            params, opt_state = constrain(params), constrain(opt_state)
        return TrainState(params, opt_state, state.step + 1, state.rng), loss

    if prox_mu > 0.0:
        mu = float(prox_mu)

        @partial(jax.jit, donate_argnums=(0,))
        def train_step_prox(
            state: TrainState, batch, anchor
        ) -> tuple[TrainState, jnp.ndarray]:
            note_compile(tuple(batch["input_ids"].shape))
            step_rng = jax.random.fold_in(state.rng, state.step)

            def prox_loss(p, batch, step_rng):
                return loss_rm(p, batch, step_rng) + 0.5 * mu * prox_sq(
                    p, anchor
                )

            loss, grads = jax.value_and_grad(prox_loss)(
                state.params, batch, step_rng
            )
            return _apply_grads(state, loss, grads)

        return ledger.timed(site, train_step_prox)

    @partial(jax.jit, donate_argnums=(0,))
    def train_step(state: TrainState, batch) -> tuple[TrainState, jnp.ndarray]:
        # Compile-ledger trace hook (obs/profile.py): this body runs once
        # per traced shape, so the note IS a compile event, never a call.
        note_compile(tuple(batch["input_ids"].shape))
        step_rng = jax.random.fold_in(state.rng, state.step)
        loss, grads = jax.value_and_grad(loss_rm)(
            state.params, batch, step_rng
        )
        return _apply_grads(state, loss, grads)

    return ledger.timed(site, train_step)


def make_eval_step(
    model: DDoSClassifier,
    *,
    gather: Callable | None = None,
    site: str = "engine.eval_step",
) -> Callable:
    """Jitted eval step -> (BinaryCounts, P(class 1) probs for ROC/PR).
    ``gather`` places shard-at-rest params replicated at use (the FSDP
    entry :func:`make_fsdp_eval_step`); no remat needed — eval saves no
    residuals."""
    ledger = default_ledger()
    note_compile = ledger.hook(site)

    @jax.jit
    def eval_step(params, batch, valid) -> tuple[BinaryCounts, jnp.ndarray]:
        note_compile(tuple(batch["input_ids"].shape))
        if gather is not None:
            params = gather(params)
        return eval_counts(model, params, batch, valid)

    return ledger.timed(site, eval_step)


# ----------------------------------------------------- FSDP (sharded) steps
#: checkpoint_name tag on every FSDP all-gather output: the remat policy
#: below saves EVERYTHING ELSE, so the backward pass re-runs only the
#: gathers instead of retaining full-size gathered weights as residuals
#: — ZeRO-3's recompute-the-gather, not full activation remat.
FSDP_GATHER_NAME = "fsdp_gathered"


def _tag_gather(gather: Callable) -> Callable:
    """checkpoint_name-tag every gathered leaf — the value the FSDP
    remat policy refuses to save (re-gathered in the backward)."""
    from jax.ad_checkpoint import checkpoint_name

    def tagged(params):
        return jax.tree.map(
            lambda x: checkpoint_name(x, FSDP_GATHER_NAME), gather(params)
        )

    return tagged


def _fsdp_policy() -> Callable | None:
    """Remat policy for the FSDP loss region: save every forward
    intermediate EXCEPT the all-gathered weights — the checkpoint_name-
    tagged gather outputs AND the sharding-constraint outputs feeding
    them. The stock except-these-names policy alone is NOT enough: the
    un-named constraint output is the same full-size array and the
    policy happily saves it, so the backward would retain the gathered
    weights anyway (verified against the saved-residual list; the
    partial eval saves the nearest policy-saveable producer). None when
    this jax build lacks named policies or moved the constraint
    primitive — callers fall back to plain remat (memory still bounded,
    at a forward replay's extra cost)."""
    named = getattr(
        jax.checkpoint_policies, "save_anything_except_these_names", None
    )
    if named is None:  # pragma: no cover - older jax fallback
        return None
    try:
        from jax._src.pjit import sharding_constraint_p
    except Exception:  # pragma: no cover - jax internals moved
        return None
    base = named(FSDP_GATHER_NAME)

    def policy(prim, *args, **params):
        if prim is sharding_constraint_p:
            return False
        return base(prim, *args, **params)

    return policy


def fsdp_remat_loss(fn: Callable) -> Callable:
    """Wrap the WHOLE loss computation (the gather runs inside ``fn``)
    in ``jax.remat`` under the FSDP policy, so the only values the
    backward recomputes are the all-gathers: full-size gathered weights
    are never retained as residuals and the activations stay saved (no
    forward replay). The remat must wrap the loss, not just the gather
    — a remat region's outputs consumed by un-rematted downstream code
    are always saved, which would defeat the policy."""
    policy = _fsdp_policy()
    if policy is None:  # pragma: no cover - older jax fallback
        return jax.remat(fn)
    return jax.remat(fn, policy=policy)


def make_fsdp_train_step(
    model: DDoSClassifier,
    optimizer: optax.GradientTransformation,
    warmup_steps: int,
    *,
    prox_mu: float = 0.0,
    gather: Callable,
    constrain: Callable,
    site: str = "engine.fsdp_train_step",
) -> Callable:
    """The engine train step, spec-parameterized for FSDP shard-at-rest:

    * ``gather(params) -> params`` places every leaf replicated (the
      all-gather-at-use); it runs inside a ``jax.remat`` region tagged so
      the backward RE-GATHERS instead of retaining full-size weights.
    * ``constrain(tree) -> tree`` pins a tree back onto its per-leaf
      shard specs — applied to the grads (the reduce-scatter feeding
      sharded Adam), the updated params, and the new optimizer state, so
      the static state never exists full-size outside the gather window.

    SAME implementation as :func:`make_train_step` — this is a thin
    named entry (its own compile-ledger site) over the base builder's
    gather=/constrain= parameterization, so the PRNG stream, warmup,
    and update arithmetic CANNOT drift; the trajectory matches the
    replicated mesh to fp32 reduction-order ulps (the grad
    reduce-scatter may sum partials in a different order than the
    all-reduce; documented and A/B allclose-pinned like the PR-2
    meshed-vs-single contract)."""
    return make_train_step(
        model,
        optimizer,
        warmup_steps,
        prox_mu=prox_mu,
        gather=gather,
        constrain=constrain,
        site=site,
    )


def make_fsdp_eval_step(
    model: DDoSClassifier,
    *,
    gather: Callable,
    site: str = "engine.fsdp_eval_step",
) -> Callable:
    """:func:`make_eval_step` over shard-at-rest params: one gather at
    use, no remat needed (eval saves no residuals)."""
    return make_eval_step(model, gather=gather, site=site)


@lru_cache(maxsize=None)
def _cached_engine_steps(model_cfg: ModelConfig, train_cfg: TrainConfig):
    """Process-wide memo of the jitted single-client programs, keyed on
    the frozen configs they are pure functions of: every Trainer built
    with equal configs (multi-round CLI flows, warm starts, the test
    suite) shares one set of compiled executables. Callers go through
    :func:`_engine_steps`, which canonicalizes step-irrelevant fields out
    of the key."""
    model = DDoSClassifier(model_cfg)
    optimizer = make_optimizer(train_cfg)
    return (
        model,
        optimizer,
        make_train_step(
            model,
            optimizer,
            warmup_steps=train_cfg.warmup_steps,
            prox_mu=train_cfg.prox_mu,
        ),
        make_eval_step(model),
    )


def step_key_cfg(train_cfg: TrainConfig) -> TrainConfig:
    """Zero the TrainConfig fields the compiled programs don't read (host
    loop/init/telemetry knobs) so e.g. seed-only variations share one
    cache entry. Conservative direction: a newly added field defaults to
    being part of the key (worst case a lost share, never wrong sharing).
    The ONE canonicalizer for every compiled-program memo key — the FSDP
    step cache (train/client_mesh._fsdp_steps) keys on it too, so the
    field list can't drift between the two caches."""
    return replace(train_cfg, seed=0, epochs_per_round=1, log_every=0)


def _engine_steps(model_cfg: ModelConfig, train_cfg: TrainConfig):
    """Memo entry point: canonicalize the key, then hit the cache."""
    return _cached_engine_steps(model_cfg, step_key_cfg(train_cfg))


def adopt_aggregate_with_fresh_opt(trainer: Any, state: Any, aggregated: Any) -> Any:
    """The aggregate-adoption semantics every TCP-client trainer shares:
    fresh optimizer from the received aggregate (``trainer.init_state``
    owns placement — engine, meshed, or C=1 fedseq), continuing step
    counter. One implementation so the plain, data-parallel, and
    seq-parallel clients can never drift apart here."""
    trained_steps = int(state.step)
    state = trainer.init_state(params=aggregated)
    return state._replace(step=jnp.asarray(trained_steps, jnp.int32))


class Trainer:
    """Single-client engine: fit for E epochs, evaluate with full metrics."""

    def __init__(
        self,
        model_cfg: ModelConfig,
        train_cfg: TrainConfig,
        *,
        pad_id: int = 0,
        drop_remainder: bool = True,
    ):
        self.model_cfg = model_cfg
        self.train_cfg = train_cfg
        self.pad_id = pad_id
        self.drop_remainder = drop_remainder
        # One-slot epoch prefetch (train/batches.PrefetchSlot): the
        # TCP round loop arms it before the federated exchange so the
        # next epoch's first batches materialize while the client waits
        # on the aggregate reply. Keyed on (split id, epoch, batch_size)
        # so a mismatched consume falls back to the live iterator.
        self._prefetch = PrefetchSlot()
        self.model, self.optimizer, self.train_step, self.eval_step = (
            _engine_steps(model_cfg, train_cfg)
        )
        # FedProx anchor (train_cfg.prox_mu > 0): the round-start params
        # the proximal term pulls toward — the last adopted aggregate,
        # or the fit-entry params before any round completed. Fresh
        # buffers always (jnp.copy): the train step donates the state,
        # so an aliased anchor would be invalidated mid-epoch.
        self._prox_anchor = None
        # Step-time attribution (obs/profile.py): None unless profiling
        # is armed process-wide (--profile-stride / ObsConfig) — the hot
        # loop then runs the literal pre-profiling path. Re-checked at
        # fit time because the CLI installs the stride after trainers
        # are built.
        self.step_profiler = maybe_step_profiler("train")

    def init_state(self, seed: int | None = None, params: Any | None = None) -> TrainState:
        seed = self.train_cfg.seed if seed is None else seed
        rng = jax.random.key(seed, impl=self.train_cfg.prng_impl)
        if params is None:
            params = init_params(self.model, self.model_cfg, rng)
        params = self._place_init_params(params)
        return TrainState(
            params=params,
            opt_state=self._init_opt_state(params),
            step=jnp.zeros((), jnp.int32),
            rng=jax.random.fold_in(rng, 1),
        )

    def _place_init_params(self, params: Any) -> Any:
        """Hook: where freshly built/adopted params live BEFORE the
        optimizer init sees them. The seed/PRNG/param-init sequence
        above is the ONE trajectory-defining implementation; subclasses
        override only placement (the FSDP trainer scatters onto shards
        so the moments inherit the layout)."""
        return params

    def _init_opt_state(self, params: Any) -> Any:
        """Hook: optimizer-state construction (the FSDP trainer jits it
        so sharding propagation keeps zeros_like moments sharded)."""
        return self.optimizer.init(params)

    def evaluate_state(
        self, state: TrainState, split: TokenizedSplit, **kw: Any
    ) -> dict:
        """Metrics from the live training state — the uniform entry the
        TCP client uses so meshed trainers (whose state params are stacked
        or sharded) evaluate without a host round-trip."""
        return self.evaluate(state.params, split, **kw)

    def host_params(self, state: TrainState) -> Any:
        """Gather the state's params to host numpy — the wire-upload form
        the TCP client feeds FederatedClient.exchange. The single-device
        engine's gather is a plain readback; the replicated mesh trainer
        keeps this (one replica reads back); the FSDP trainer overrides
        it to return device-backed shards so the streamed upload's
        pack-time gather stays lazy."""
        return jax.tree.map(np.asarray, state.params)

    def adopt_aggregate(self, state: TrainState, aggregated: Any) -> TrainState:
        """Continue the next round FROM a received aggregate with a fresh
        Adam (every reference re-launch constructs a new optimizer,
        client1.py:380) but a continuing step counter (LR warmup). The
        single shared implementation for the plain and meshed TCP clients
        — ``init_state`` places the aggregate, so a meshed subclass
        scatters it straight onto its device mesh with no intermediate
        full-replica state. Under FedProx the adopted aggregate IS the
        next round's proximal anchor (w_round_start)."""
        state = adopt_aggregate_with_fresh_opt(self, state, aggregated)
        if self.train_cfg.prox_mu > 0.0:
            self._prox_anchor = jax.tree.map(jnp.copy, state.params)
        return state

    def _round_anchor(self, state: TrainState) -> Any:
        """The FedProx anchor for this fit: the last adopted aggregate,
        or (first round — no aggregate exists yet) a copy of the
        fit-entry params, for which the proximal term starts at zero
        exactly as FedProx prescribes."""
        if self._prox_anchor is None:
            self._prox_anchor = jax.tree.map(jnp.copy, state.params)
        return self._prox_anchor

    def epoch_batches(
        self, split: TokenizedSplit, epoch: int, batch_size: int
    ) -> Iterator[dict]:
        # A matching armed prefetch (prefetch_epoch) serves this epoch's
        # head from the background-materialized buffer; the tail — and
        # any mismatched key — is the live iterator below, so the batch
        # sequence is identical either way.
        it = self._prefetch.consume((id(split), int(epoch), int(batch_size)))
        if it is not None:
            return it
        return self._epoch_iterator(split, epoch, batch_size)

    def _epoch_iterator(self, split, epoch: int, batch_size: int):
        """The epoch's shuffled iterator — the SINGLE derivation of its
        permutation seed, shared by the live path and the armed prefetch
        so a prefetched head can never train on different batches.

        drop_remainder=False (DataConfig.drop_remainder): the final short
        batch trains at its own shape (one extra XLA compilation) — the
        reference DataLoader's drop_last=False semantics (client1.py:370),
        exact per-batch mean loss included. The default drops it for a
        single compiled shape."""
        return batch_iterator(
            split,
            batch_size,
            shuffle=True,
            seed=self.train_cfg.seed * 100_003 + epoch,
            drop_remainder=self.drop_remainder,
        )

    def prefetch_epoch(
        self, split: TokenizedSplit, epoch: int, batch_size: int, *, k: int = 2
    ):
        """Arm the one-slot prefetch for ``epoch``: its permutation and
        first ``k`` batch gathers run on a background thread NOW (the TCP
        client calls this right before blocking on the round exchange, so
        reply latency is hidden behind next-round input-pipeline work).
        The next matching ``epoch_batches`` consumes it; determinism is
        unchanged (same iterator, evaluated early). Returns the
        EpochPrefetcher so the caller can report its measured span."""
        return self._prefetch.arm(
            (id(split), int(epoch), int(batch_size)),
            lambda: self._epoch_iterator(split, epoch, batch_size),
            k=k,
        )

    def _armed_profiler(self):
        """The fit loop's step profiler: the one built at construction,
        or a late arm when the CLI installed the stride afterwards, with
        a fresh reporting window either way. None = profiling off (the
        zero-overhead path)."""
        prof = self.step_profiler
        if prof is None:
            prof = self.step_profiler = maybe_step_profiler("train")
        if prof is not None:
            prof.begin_window()
        return prof

    def step_profile_attrs(self) -> dict:
        """Sampled step p50/p95 attrs of the last fit window (ms) for
        stamping on the client-local span; {} when profiling is off."""
        prof = self.step_profiler
        return prof.span_attrs() if prof is not None else {}

    def fit(
        self,
        state: TrainState,
        split: TokenizedSplit,
        *,
        batch_size: int = 16,
        epochs: int | None = None,
        epoch_offset: int = 0,
        tag: str = "",
    ) -> tuple[TrainState, list[float]]:
        """Train for E epochs. ``epoch_offset`` decorrelates the shuffle
        order across repeated fit() calls (e.g. pass ``round * E`` from a
        multi-round driver); without it every round would replay the same
        batch permutations."""
        step_fn = self.train_step
        if self.train_cfg.prox_mu > 0.0:
            # FedProx: the prox-variant step takes the round anchor as a
            # third argument (same jitted program across rounds — the
            # anchor is data, not a closure constant).
            anchor = self._round_anchor(state)

            def step_fn(s, b, _step=self.train_step, _a=anchor):
                return _step(s, b, _a)

        return self._fit_loop(
            state,
            split,
            step_fn,
            batch_size=batch_size,
            epochs=epochs,
            epoch_offset=epoch_offset,
            tag=tag,
        )

    def _fit_loop(
        self,
        state: TrainState,
        split: TokenizedSplit,
        step_fn: Callable[[TrainState, dict], tuple[TrainState, jnp.ndarray]],
        *,
        batch_size: int,
        epochs: int | None,
        epoch_offset: int,
        tag: str,
        loss_label: str = "Average Loss",
    ) -> tuple[TrainState, list[float]]:
        """Shared epoch loop (plain fit and the KD step both ride it)."""
        epochs = self.train_cfg.epochs_per_round if epochs is None else epochs
        epoch_losses: list[float] = []
        telemetry = make_step_telemetry(
            self.train_cfg.log_every, prefix=tag, label=loss_label
        )
        prof = self._armed_profiler()
        first_memory = prof is not None
        last_loss = None  # carried ACROSS epochs: the drain fence target
        for epoch in range(epoch_offset, epoch_offset + epochs):
            # Collect device scalars and sync once per epoch — float(loss)
            # per step would block async dispatch and stall the TPU.
            losses: list[jnp.ndarray] = []
            for batch, sampled in profiled_step_iter(
                prof, self.epoch_batches(split, epoch, batch_size)
            ):
                if sampled:
                    # Fenced sampled step: drain the async backlog so
                    # the measurement is this step's own device work,
                    # then split dispatch from device-execute.
                    prof.drain(last_loss)
                    t0 = prof.clock()
                    state, loss = step_fn(state, batch)
                    prof.note_dispatch(prof.clock() - t0)
                    prof.fence(loss)
                else:
                    state, loss = step_fn(state, batch)
                losses.append(loss)
                last_loss = loss
                telemetry(loss, batch_size)
                if first_memory:
                    first_memory = False
                    note_memory("post-first-step")
            avg = float(jnp.stack(losses).mean()) if losses else 0.0
            epoch_losses.append(avg)
            log.info(
                f"{tag}Epoch [{epoch - epoch_offset + 1}/{epochs}], "
                f"{loss_label}: {avg:.4f}"
            )
        return state, epoch_losses

    def evaluate(
        self,
        params: Any,
        split: TokenizedSplit,
        *,
        batch_size: int = 16,
        collect_probs: bool = True,
    ) -> dict:
        """Five reference metrics + confusion matrix (+ labels/probs for
        ROC & PR curves, the reference's evaluate_model return shape,
        client1.py:150)."""
        padded, valid = pad_split_to_batch(split, batch_size, pad_id=self.pad_id)
        # None-init: the first batch's counts type (BinaryCounts for K=2,
        # ClassCounts for K>2) decides the accumulator — eval_counts'
        # static branch keeps the binary path bit-identical.
        totals: BinaryCounts | ClassCounts | None = None
        # Device arrays accumulate; host conversion happens once after the
        # loop so eval pipelines like fit() does.
        probs_dev: list[jnp.ndarray] = []
        valid_slices: list[np.ndarray] = []
        for start in range(0, len(padded), batch_size):
            sl = slice(start, start + batch_size)
            batch = {
                "input_ids": padded.input_ids[sl],
                "attention_mask": padded.attention_mask[sl],
                "labels": padded.labels[sl],
            }
            counts, probs = self.eval_step(batch=batch, params=params, valid=valid[sl])
            totals = counts if totals is None else totals + counts
            if collect_probs:
                probs_dev.append(probs)
                valid_slices.append(valid[sl])
        if totals is None:
            totals = BinaryCounts.zero()
        metrics = (
            finalize_class_metrics(totals)
            if isinstance(totals, ClassCounts)
            else finalize_metrics(totals)
        )
        if collect_probs:
            if probs_dev:
                all_probs = np.asarray(jnp.concatenate(probs_dev))
                metrics["probs"] = all_probs[np.concatenate(valid_slices) == 1]
            else:
                metrics["probs"] = np.array([])
            metrics["labels"] = split.labels.copy()
        return metrics
