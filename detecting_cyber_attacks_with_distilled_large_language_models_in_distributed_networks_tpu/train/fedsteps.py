"""Jitted federated step construction: the SPMD programs the trainer runs.

One stacked ``[C, ...]`` parameter tree sharded over the ``clients`` mesh
axis; one vmapped train step advances every client in lockstep on its
private shard (the reference instead runs N separate OS processes,
client1.py:96-115 per process). ``build_federated_steps`` is a pure
function of (config, model, optimizer, shardings); ``aggregate_round`` is
the round-boundary dispatch over those steps — it takes the trainer as a
facade (cfg/steps/_host/_dp_key) and is called only through
``FederatedTrainer.aggregate``. Lifecycle and multi-host sync stay in
train/federated.py.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

import numpy as np

from ..obs.profile import default_ledger
from ..parallel.fedavg import make_fedavg_step
from ..train.engine import (
    apply_warmup,
    eval_counts,
    loss_fn,
    masked_loss_fn,
    prox_sq,
)
from ..utils.logging import get_logger

log = get_logger()


def make_packed_step(
    objective,
    optimizer,
    wsteps: int,
    mu: float,
    *,
    gather: Callable | None = None,
    constrain: Callable | None = None,
) -> Callable:
    """The SINGLE per-client packed step builder (shared by the dense and
    3-axis fedseq paths — their update math must never diverge).

    ``objective(params, batch, step_rng, anchor) -> (objective, task)``
    supplies the loss; everything else — the per-step rng fold off the
    lockstep counter, Adam, warmup, donation — is identical to one lane
    of the stacked vmapped step. Signature of the returned program:
    ``(cstate, batch[, anchor]) -> (cstate, task_loss)`` with
    ``cstate = (params, opt_state, step, rng)`` (one client's buffers,
    donated).

    ``gather``/``constrain`` spec-parameterize the step for FSDP
    shard-at-rest state (train/engine.py's contract: gather runs inside
    a remat region so the backward re-gathers; constrain reduce-scatters
    grads and pins the updated params/opt leaves back onto their
    shards). None/None (the default) is the literal replicated step."""

    note_compile = default_ledger().hook("fed.packed_step")
    if gather is not None:
        from .engine import _tag_gather, fsdp_remat_loss

        # The remat wraps the WHOLE objective with the tagged gather
        # inside (engine.fsdp_remat_loss): wrapping only the gather
        # would save its full-size outputs as residuals anyway.
        base_objective, tagged = objective, _tag_gather(gather)
        objective = fsdp_remat_loss(
            lambda p, b, r, a: base_objective(tagged(p), b, r, a)
        )

    def body(cstate, batch, anchor):
        note_compile(tuple(batch["input_ids"].shape))
        params, opt_state, step, rng = cstate
        step_rng = jax.random.fold_in(rng, step)
        (_, task), grads = jax.value_and_grad(
            lambda p: objective(p, batch, step_rng, anchor),
            has_aux=True,
        )(params)
        if constrain is not None:
            grads = constrain(grads)
        updates, new_opt = optimizer.update(grads, opt_state, params)
        updates = apply_warmup(updates, step, wsteps)
        new_params = optax.apply_updates(params, updates)
        if constrain is not None:
            new_params = constrain(new_params)
            new_opt = constrain(new_opt)
        return ((new_params, new_opt, step + 1, rng), task)

    if mu > 0.0:
        jitted = jax.jit(body, donate_argnums=(0,))
    else:
        jitted = jax.jit(
            lambda cstate, batch: body(cstate, batch, None),
            donate_argnums=(0,),
        )
    return default_ledger().timed("fed.packed_step", jitted)


class FedState(NamedTuple):
    """Stacked per-client training state; every leaf's axis 0 is clients."""

    params: Any  # [C, ...]
    opt_state: Any  # [C, ...]
    step: jnp.ndarray  # scalar int32 — lockstep across clients
    rngs: jax.Array  # [C] dropout keys
    # FedOpt server-optimizer state (single-model shaped, replicated);
    # None under plain FedAvg. Persists across rounds — the per-round
    # client optimizer reset does not touch it.
    server_opt: Any = None


class FedSteps(NamedTuple):
    """The jitted programs + lazy builders behind a FederatedTrainer."""

    train_step: Callable  # (state, batch[, anchor]) -> (state, [C] losses)
    build_ragged_step: Callable  # () -> ragged train step (compiled on demand)
    eval_step: Callable  # (params, batch, valid) -> (BinaryCounts, probs)
    fedavg_step: Callable
    server_tx: Any  # optax server optimizer | None
    server_agg_step: Callable | None
    dp_fedavg_step: Callable | None
    opt_init: Callable  # stacked params -> stacked opt state
    replicate: Callable  # clients-sharded tree -> replicated tree
    # () -> per-client PACKED step (compiled on demand): the client-packing
    # fast path for a single-device mesh — see build_packed_step below.
    build_packed_step: Callable = None


def build_federated_steps(
    cfg,
    model,
    optimizer,
    sh,
    *,
    gather: Callable | None = None,
    constrain: Callable | None = None,
) -> FedSteps:
    """Compile-ready step closures for one experiment configuration.

    ``sh``: parallel.mesh.FedShardings — fixes how every input/output lays
    over the ``clients x data`` mesh, so jit inserts the collectives (the
    reference's entire TCP protocol, client1.py:246-336) at trace time.

    ``gather``/``constrain`` spec-parameterize the STACKED steps for FSDP
    shard-at-rest state — the same callable contract ``make_packed_step``
    takes, lifted to the ``[C, ...]`` trees: ``gather(stacked_params)``
    replicates every leaf over the fsdp axis (the all-gather AT USE,
    tagged + rematted so the backward re-gathers instead of retaining
    full-size weights), ``constrain(stacked_tree)`` pins grads and the
    updated params/opt leaves back onto their shards. Both callables see
    STACKED trees (they run outside the client vmap — per-lane sharding
    constraints cannot express the stacked layout), so callers build them
    from the stacked specs. None/None is the literal replicated program
    — byte-identical construction to the pre-parameterized builder."""
    csh, bsh = sh.client, sh.batch
    if (gather is None) != (constrain is None):
        raise ValueError(
            "gather and constrain parameterize the same FSDP layout — "
            "pass both or neither"
        )
    if gather is not None:
        from .engine import _tag_gather, fsdp_remat_loss

        tagged = _tag_gather(gather)
    mu = float(cfg.fed.prox_mu)
    wsteps = cfg.train.warmup_steps

    def local_loss(p, batch, rng, anchor):
        """Returns (training objective, task loss): gradients flow from
        the first, logs/round records report the second so FedProx and
        FedAvg loss curves stay comparable."""
        task = loss_fn(model, p, batch, rng)
        total = task
        if mu > 0.0:
            # FedProx proximal term vs the round-start globals —
            # trace-time constant, zero cost at mu=0 (plain FedAvg).
            total = task + 0.5 * mu * prox_sq(p, anchor)
        return total, task

    def per_client_step(params, opt_state, batch, rng, anchor, step):
        (_, task), grads = jax.value_and_grad(
            lambda p: local_loss(p, batch, rng, anchor), has_aux=True
        )(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        updates = apply_warmup(updates, step, wsteps)
        return optax.apply_updates(params, updates), opt_state, task

    state_sh = FedState(csh, csh, sh.replicated, csh, sh.replicated)
    batch_sh = {"input_ids": bsh, "attention_mask": bsh, "labels": bsh}
    ledger = default_ledger()
    note_train = ledger.hook("fed.train_step")

    def _step_body(state: FedState, batch, anchor):
        note_train(tuple(batch["input_ids"].shape))
        step_rngs = jax.vmap(jax.random.fold_in, in_axes=(0, None))(
            state.rngs, state.step
        )
        params, opt_state, losses = jax.vmap(
            per_client_step,
            in_axes=(0, 0, 0, 0, 0 if mu > 0.0 else None, None),
        )(state.params, state.opt_state, batch, step_rngs, anchor, state.step)
        return (
            state._replace(
                params=params, opt_state=opt_state, step=state.step + 1
            ),
            losses,  # [C]
        )

    def _fsdp_step_body(state: FedState, batch, anchor):
        """The gather/constrain-parameterized stacked step: grads come
        from ONE rematted stacked objective (per-client losses depend
        only on their own lane, so grad of the sum IS the stacked
        per-client grads), gathered at use and reduce-scattered back,
        with the optimizer update vmapped over the constrained grads —
        the same math as ``_step_body``, laid out for shard-at-rest."""
        note_train(tuple(batch["input_ids"].shape))
        step_rngs = jax.vmap(jax.random.fold_in, in_axes=(0, None))(
            state.rngs, state.step
        )

        def stacked_objective(sp, b, r, a):
            totals, tasks = jax.vmap(
                local_loss, in_axes=(0, 0, 0, 0 if mu > 0.0 else None)
            )(tagged(sp), b, r, a)
            return totals.sum(), tasks

        (_, losses), grads = jax.value_and_grad(
            fsdp_remat_loss(stacked_objective), has_aux=True
        )(state.params, batch, step_rngs, anchor)
        grads = constrain(grads)
        updates, opt_state = jax.vmap(optimizer.update)(
            grads, state.opt_state, state.params
        )
        updates = apply_warmup(updates, state.step, wsteps)
        params = optax.apply_updates(state.params, updates)
        params = constrain(params)
        opt_state = constrain(opt_state)
        return (
            state._replace(
                params=params, opt_state=opt_state, step=state.step + 1
            ),
            losses,  # [C]
        )

    if gather is not None:
        # No explicit in/out shardings: the constrain calls pin the FSDP
        # layout inside the program and inputs carry the caller's
        # placements — an out_shardings of ``csh`` here would force a
        # full re-gather at every step boundary.
        body = _fsdp_step_body
        if mu > 0.0:
            train_step = jax.jit(body, donate_argnums=(0,))
        else:
            train_step = jax.jit(
                lambda state, batch: body(state, batch, None),
                donate_argnums=(0,),
            )
    elif mu > 0.0:
        # FedProx signature: (state, batch, anchor). The anchor is the
        # stacked round-start params — a separate buffer, NOT the
        # donated state.params.
        train_step = partial(
            jax.jit,
            donate_argnums=(0,),
            in_shardings=(state_sh, batch_sh, csh),
            out_shardings=(state_sh, csh),
        )(_step_body)
    else:
        # Plain FedAvg signature: (state, batch) — no anchor transfer.
        train_step = partial(
            jax.jit,
            donate_argnums=(0,),
            in_shardings=(state_sh, batch_sh),
            out_shardings=(state_sh, csh),
        )(lambda state, batch: _step_body(state, batch, None))
    train_step = ledger.timed("fed.train_step", train_step)

    def per_client_step_masked(params, opt_state, batch, rng, anchor):
        """Row-masked variant for the ragged stacked path: the loss
        averages over the batch's valid rows only, and a client whose
        lockstep batch is ALL padding keeps its params/optimizer state
        untouched (zero grads through Adam would still move the moments
        — a phantom update an independent run never takes)."""

        def obj(p):
            task = masked_loss_fn(model, p, batch, rng)
            total = task
            if mu > 0.0:
                total = task + 0.5 * mu * prox_sq(p, anchor)
            return total, task

        (_, task), grads = jax.value_and_grad(obj, has_aux=True)(params)
        updates, new_opt = optimizer.update(grads, opt_state, params)
        # Warmup rides the client's OWN executed-step count (see
        # train/batches.py federated_batches_ragged), not the shared
        # lockstep counter — an idling client's ramp must not advance.
        updates = apply_warmup(updates, batch["warmup_step"][0], wsteps)
        new_params = optax.apply_updates(params, updates)
        has = batch["valid"].sum() > 0
        params = jax.tree.map(
            lambda n, o: jnp.where(has, n, o), new_params, params
        )
        opt_state = jax.tree.map(
            lambda n, o: jnp.where(has, n, o), new_opt, opt_state
        )
        return params, opt_state, task, has.astype(jnp.float32)

    ragged_batch_sh = dict(batch_sh, valid=bsh, warmup_step=bsh)
    note_ragged = ledger.hook("fed.ragged_step")

    def _ragged_body(state: FedState, batch, anchor):
        note_ragged(tuple(batch["input_ids"].shape))
        step_rngs = jax.vmap(jax.random.fold_in, in_axes=(0, None))(
            state.rngs, state.step
        )
        params, opt_state, losses, has = jax.vmap(
            per_client_step_masked,
            in_axes=(0, 0, 0, 0, 0 if mu > 0.0 else None),
        )(state.params, state.opt_state, batch, step_rngs, anchor)
        return (
            state._replace(
                params=params, opt_state=opt_state, step=state.step + 1
            ),
            (losses, has),  # [C] masked losses, [C] 0/1 batch-had-rows
        )

    @lru_cache(maxsize=1)
    def build_packed_step():
        """Per-client PACKED train step — the client-packing fast path.

        On a single-device mesh the stacked vmapped program pays for its
        layout: every GEMM carries a client batch dim and each step
        re-slices/re-stacks nothing but still runs batched-weight
        kernels. Measured on the v5e chip (PARITY.md r5 decomposition):
        the stacked-vmap product step runs 42.3% MFU vs 57.2% for the
        SAME math dispatched as independent per-client engine steps —
        the fit loop unstacks once per fit, steps each client's state
        through this program, and restacks at the end. Semantically
        identical to the vmapped step (same per-client rng fold, same
        lockstep counter, same Adam); bit-level trajectory parity holds
        under threefry dropout keys (pinned by
        test_federated.py::test_packed_fit_matches_vmapped) — the default
        rbg impl generates layout-dependent bitstreams, so there the two
        paths draw different, equally distributed dropout masks.

        NOTE: the packed step runs SINGLE-client state — the stacked
        gather/constrain callables do not apply to its lane-shaped trees,
        so the FSDP-parameterized builder keeps the packed path
        replicated (single-device packing and shard-at-rest are disjoint
        deployments; a packed FSDP step is built directly via
        ``make_packed_step(gather=, constrain=)`` with lane-level
        callables)."""
        return make_packed_step(local_loss, optimizer, wsteps, mu)

    def _fsdp_ragged_body(state: FedState, batch, anchor):
        """Row-masked stacked step under gather/constrain: same sum-trick
        stacked objective as ``_fsdp_step_body`` over the masked loss,
        with the all-padding-client freeze (where-merge) riding inside
        the vmapped update and the outputs pinned back onto shards."""
        note_ragged(tuple(batch["input_ids"].shape))
        step_rngs = jax.vmap(jax.random.fold_in, in_axes=(0, None))(
            state.rngs, state.step
        )

        def lane_loss(p, b, r, a):
            task = masked_loss_fn(model, p, b, r)
            total = task
            if mu > 0.0:
                total = task + 0.5 * mu * prox_sq(p, a)
            return total, task

        def stacked_objective(sp, b, r, a):
            totals, tasks = jax.vmap(
                lane_loss, in_axes=(0, 0, 0, 0 if mu > 0.0 else None)
            )(tagged(sp), b, r, a)
            return totals.sum(), tasks

        (_, losses), grads = jax.value_and_grad(
            fsdp_remat_loss(stacked_objective), has_aux=True
        )(state.params, batch, step_rngs, anchor)
        grads = constrain(grads)

        def upd(g, o, p, b):
            updates, new_opt = optimizer.update(g, o, p)
            updates = apply_warmup(updates, b["warmup_step"][0], wsteps)
            new_params = optax.apply_updates(p, updates)
            has = b["valid"].sum() > 0
            new_params = jax.tree.map(
                lambda n, old: jnp.where(has, n, old), new_params, p
            )
            new_opt = jax.tree.map(
                lambda n, old: jnp.where(has, n, old), new_opt, o
            )
            return new_params, new_opt, has.astype(jnp.float32)

        params, opt_state, has = jax.vmap(upd)(
            grads, state.opt_state, state.params, batch
        )
        params = constrain(params)
        opt_state = constrain(opt_state)
        return (
            state._replace(
                params=params, opt_state=opt_state, step=state.step + 1
            ),
            (losses, has),
        )

    @lru_cache(maxsize=1)
    def build_ragged_step():
        """Built on first ragged fit_local (equal-client runs never pay
        the extra compilation); memoized so same-config trainers share the
        compiled executable."""
        if gather is not None:
            body = _fsdp_ragged_body
            if mu > 0.0:
                jitted = jax.jit(body, donate_argnums=(0,))
            else:
                jitted = jax.jit(
                    lambda state, batch: body(state, batch, None),
                    donate_argnums=(0,),
                )
            return ledger.timed("fed.ragged_step", jitted)
        if mu > 0.0:
            jitted = partial(
                jax.jit,
                donate_argnums=(0,),
                in_shardings=(state_sh, ragged_batch_sh, csh),
                out_shardings=(state_sh, (csh, csh)),
            )(_ragged_body)
        else:
            jitted = partial(
                jax.jit,
                donate_argnums=(0,),
                in_shardings=(state_sh, ragged_batch_sh),
                out_shardings=(state_sh, (csh, csh)),
            )(lambda state, batch: _ragged_body(state, batch, None))
        return ledger.timed("fed.ragged_step", jitted)

    note_eval = ledger.hook("fed.eval_step")

    @partial(
        jax.jit,
        in_shardings=(
            csh,
            {"input_ids": bsh, "attention_mask": bsh, "labels": bsh},
            bsh,
        ),
    )
    def eval_step(stacked_params, batch, valid):
        note_eval(tuple(batch["input_ids"].shape))
        return jax.vmap(lambda p, b, v: eval_counts(model, p, b, v))(
            stacked_params, batch, valid
        )

    eval_step = ledger.timed("fed.eval_step", eval_step)

    if cfg.fed.server_opt_enabled():
        from ..parallel.fedavg import make_server_optimizer, weighted_mean

        server_tx = make_server_optimizer(cfg.fed)

        @partial(
            jax.jit,
            in_shardings=(csh, csh, None, None, sh.replicated),
            out_shardings=(csh, sh.replicated),
        )
        def server_agg_step(stacked_params, anchor, w, m, server_state):
            """FedOpt round boundary: pseudo-gradient = anchor - mean
            of (possibly weighted/masked) client params; the server
            optimizer turns it into the global step, broadcast back to
            every client shard. All server math in fp32."""
            mean = weighted_mean(stacked_params, w, m)
            # Anchor rows are identical (previous round's replicated
            # output); the mean over axis 0 IS the single-model value.
            anchor1 = weighted_mean(anchor)
            g = jax.tree.map(lambda a, mn: a - mn, anchor1, mean)
            updates, new_state = server_tx.update(g, server_state, anchor1)
            new1 = optax.apply_updates(anchor1, updates)
            stacked = jax.tree.map(
                lambda n, ref: jnp.broadcast_to(n.astype(ref.dtype), ref.shape),
                new1,
                stacked_params,
            )
            return stacked, new_state

    else:
        server_tx = None
        server_agg_step = None

    if cfg.fed.dp_clip > 0.0:
        from ..parallel.dp import make_dp_fedavg_step

        dp_fedavg_step = make_dp_fedavg_step(
            sh,
            clip=float(cfg.fed.dp_clip),
            noise_multiplier=float(cfg.fed.dp_noise_multiplier),
        )
    else:
        dp_fedavg_step = None

    # vmapped optimizer init, compiled once (reset_optimizer runs it
    # every round — a fresh jit lambda per call would recompile).
    opt_init = jax.jit(
        lambda p: jax.vmap(optimizer.init)(p),
        in_shardings=(csh,),
        out_shardings=csh,
    )
    # Host-sync path for clients-sharded values: under multi-process,
    # shards on other hosts are not addressable — replicate first (an
    # all-gather over DCN), then np.asarray is local. Single process
    # short-circuits in the trainer's _host().
    replicate = jax.jit(lambda x: x, out_shardings=sh.replicated)

    return FedSteps(
        train_step=train_step,
        build_ragged_step=build_ragged_step,
        eval_step=eval_step,
        fedavg_step=make_fedavg_step(sh),
        server_tx=server_tx,
        server_agg_step=server_agg_step,
        dp_fedavg_step=dp_fedavg_step,
        opt_init=opt_init,
        replicate=replicate,
        build_packed_step=build_packed_step,
    )


@lru_cache(maxsize=None)
def _cached_federated_steps(cfg, mesh) -> FedSteps:
    from ..models.distilbert import DDoSClassifier
    from ..parallel.mesh import FedShardings
    from .engine import make_optimizer

    return build_federated_steps(
        cfg, DDoSClassifier(cfg.model), make_optimizer(cfg.train), FedShardings(mesh)
    )


def cached_federated_steps(cfg, mesh) -> FedSteps:
    """Process-wide memo of ``build_federated_steps`` keyed on the inputs
    it is a pure function of: every FederatedTrainer built with an
    equivalent (config, mesh) pair — CLI resume paths, multi-round
    drivers, the test suite — shares one set of compiled executables
    instead of re-tracing identical programs.

    The key canonicalizes the config fields the compiled programs never
    read (data pipeline, distill, output paths, host-side round/epoch/
    telemetry counts), so runs differing only in e.g. --output-dir still
    share. Conservative direction: a newly added field defaults to being
    part of the key — worst case a lost share, never wrong sharing. The
    mesh *config* stays in the key only because ExperimentConfig
    validation couples it to fed.num_clients; the mesh object itself is
    what the shardings derive from."""
    from dataclasses import replace

    from ..config import DataConfig, DistillConfig

    key_cfg = replace(
        cfg,
        # max_len rides along: ExperimentConfig validates it against the
        # model's position table.
        data=DataConfig(max_len=cfg.model.max_len),
        distill=DistillConfig(),
        train=replace(cfg.train, seed=0, epochs_per_round=1, log_every=0),
        fed=replace(cfg.fed, rounds=1),
        output_dir="outputs",
        checkpoint_dir=None,
    )
    return _cached_federated_steps(key_cfg, mesh)


def check_survivors(surviving: float, C: int, min_frac: float) -> None:
    """Single enforcement of the survivor floor (zero survivors always
    abort — a zero-mask mean would silently zero or NaN the params)."""
    if surviving == 0.0 or surviving < min_frac * C:
        raise RuntimeError(
            f"only {int(surviving)}/{C} clients survived the round "
            f"(min_client_fraction={min_frac})"
        )


def aggregate_round(
    trainer,
    state: FedState,
    *,
    weights: np.ndarray | None = None,
    client_mask: np.ndarray | None = None,
    anchor: Any | None = None,
    round_index: int = 0,
    enforce_min_fraction: bool = True,
) -> FedState:
    """The FedAvg round boundary. Enforces min_client_fraction (the
    reference instead refuses unless exactly N models arrived,
    server.py:69-71) unless ``enforce_min_fraction=False`` (the Poisson
    participation path — the caller gates faults itself and a small
    sampled cohort must not abort). With ``fed.dp_clip > 0`` the boundary
    runs DP-FedAvg (parallel/dp.py): pass the ``round_anchor`` captured
    before local training plus the round index (noise key)."""
    cfg = trainer.cfg
    C = trainer.C
    if client_mask is not None:
        check_survivors(
            float(np.asarray(client_mask).sum()),
            C,
            cfg.fed.min_client_fraction if enforce_min_fraction else 0.0,
        )
    if weights is not None:
        eff = np.asarray(weights, dtype=np.float64)
        if client_mask is not None:
            eff = eff * np.asarray(client_mask, dtype=np.float64)
        if eff.sum() <= 0.0:
            # fedavg's jitted mean clamps the divisor; a zero weight sum
            # would silently zero every parameter.
            raise ValueError(
                "effective FedAvg weight sum is zero (all-zero weights, "
                "or every weighted client masked out)"
            )
    w = None if weights is None else jnp.asarray(weights)
    m = None if client_mask is None else jnp.asarray(client_mask)
    needs_anchor = (
        trainer.dp_fedavg_step is not None or trainer.server_agg_step is not None
    )
    if needs_anchor and anchor is None:
        raise ValueError(
            "DP and/or FedOpt aggregation needs the round-start anchor "
            "— capture it with round_anchor(state) before fit_local"
        )
    if trainer.dp_fedavg_step is not None:
        if w is not None:
            raise ValueError(
                "DP aggregation is a uniform mean (FedConfig forbids "
                "weighted=True with dp_clip); do not pass weights"
            )
        base, norms = trainer.dp_fedavg_step(
            state.params, anchor, trainer._dp_key(round_index), m
        )
        # DP output is already the (uniform, noised) aggregate
        # replicated across rows; any server step consumes it as-is.
        w_srv = m_srv = None
        # Log stats over PARTICIPANTS only — masked-out clients' norms
        # never touched the aggregate and would skew clip-rate tuning.
        hn = np.asarray(trainer._host(norms))
        if client_mask is not None:
            hn = hn[np.asarray(client_mask) > 0]
        clipped = int((hn > cfg.fed.dp_clip).sum())
        log.info(
            f"[DP] round {round_index}: participant update norms "
            f"median {np.median(hn):.4g} max {hn.max():.4g}; "
            f"{clipped}/{hn.size} participants clipped at "
            f"{cfg.fed.dp_clip}"
        )
    else:
        base, w_srv, m_srv = state.params, w, m
    already_aggregated = trainer.dp_fedavg_step is not None
    if trainer.server_agg_step is not None:
        params, server_state = trainer.server_agg_step(
            base, anchor, w_srv, m_srv, state.server_opt
        )
        return state._replace(params=params, server_opt=server_state)
    if already_aggregated:
        return state._replace(params=base)
    return state._replace(params=trainer.fedavg_step(base, w_srv, m_srv))
