"""Orbax checkpoint/resume for training state.

The reference's checkpoint story is ``torch.save(model.state_dict(), ...)``
after local training and after applying the aggregate, auto-loaded on the
next launch (reference client1.py:375-377,388,403; server.py:77) — and that
warm-start is its *only* multi-round FL mechanism. Optimizer state is never
checkpointed, so every "round" silently restarts Adam moments.

Here checkpointing is first-class and complete:

* the FULL state pytree is saved — params, optimizer state, step counter,
  and per-client RNG keys — so a resumed run continues bit-for-bit;
* restore is sharding-aware: leaves land directly on the mesh shards the
  template dictates (no host-memory spike of the stacked ``[C, ...]`` tree);
* a JSON metadata blob (round number, config) rides along for bookkeeping;
* ``max_to_keep`` garbage-collects old rounds.

Typed JAX PRNG keys are not directly serializable; they are transparently
unwrapped to raw key data on save and re-wrapped (with the impl recorded in
the restore template) on load.
"""

from __future__ import annotations

import os
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

STATE_ITEM = "state"
META_ITEM = "meta"


def _is_prng_key(x: Any) -> bool:
    return isinstance(x, jax.Array) and jnp.issubdtype(x.dtype, jax.dtypes.prng_key)


def _unwrap_keys(tree: Any) -> Any:
    """Typed PRNG key leaves -> raw uint32 key data (serializable)."""
    return jax.tree.map(
        lambda x: jax.random.key_data(x) if _is_prng_key(x) else x, tree
    )


def _rewrap_keys(tree: Any, template: Any) -> Any:
    """Inverse of ``_unwrap_keys``, key impl taken from the template leaf."""

    def _wrap(restored, ref):
        if _is_prng_key(ref):
            impl = jax.random.key_impl(ref)
            return jax.random.wrap_key_data(restored, impl=impl)
        return restored

    return jax.tree.map(_wrap, tree, template, is_leaf=_is_prng_key)


def _abstract(template: Any) -> Any:
    """ShapeDtypeStructs (with shardings when present) for sharded restore."""

    def _leaf(x):
        if _is_prng_key(x):
            x = jax.random.key_data(x)
        elif isinstance(x, jax.ShapeDtypeStruct) and jnp.issubdtype(
            x.dtype, jax.dtypes.prng_key
        ):
            # Abstract (eval_shape) templates carry typed-key leaves too;
            # checkpoints store the raw key data, so describe that shape.
            x = jax.eval_shape(jax.random.key_data, x)
        sharding = getattr(x, "sharding", None)
        return jax.ShapeDtypeStruct(
            np.shape(x), np.asarray(x).dtype if not hasattr(x, "dtype") else x.dtype,
            sharding=sharding,
        )

    return jax.tree.map(_leaf, _unwrap_keys(template))


class Checkpointer:
    """Save/restore any training-state pytree (TrainState, FedState, ...).

    The restore template — typically a freshly built ``init_state()`` —
    supplies tree structure, dtypes, shardings, and PRNG-key impls; the
    checkpoint supplies the values.
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    # ------------------------------------------------------------------ save
    def save(self, step: int, state: Any, *, meta: Mapping[str, Any] | None = None) -> None:
        unwrapped = _unwrap_keys(state)
        args = {STATE_ITEM: ocp.args.StandardSave(unwrapped)}
        # The saved leaf-shape manifest (internal "_leaf_shapes" key) rides
        # the JSON meta so ANY later manager instance can check template
        # compatibility before restoring — orbax's own array metadata is
        # only readable by the manager that saved (handler registry), and
        # some orbax versions restore into mismatched template shapes
        # silently (see saved_compatible).
        # Tree-leaves order, NOT sorted: a multiset compare would miss two
        # tables swapping sizes (vocab 128/pos 140 -> vocab 140/pos 128 has
        # the identical shape multiset); leaves order is deterministic for
        # a given structure, so the positional compare is exact.
        manifest = [
            [int(d) for d in np.shape(x)] for x in jax.tree.leaves(unwrapped)
        ]
        args[META_ITEM] = ocp.args.JsonSave(
            {**(dict(meta) if meta is not None else {}), "_leaf_shapes": manifest}
        )
        self._mgr.save(step, args=ocp.args.Composite(**args))

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    # --------------------------------------------------------------- restore
    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, template: Any, *, step: int | None = None) -> Any:
        """Restore the state saved at ``step`` (default: latest)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                **{STATE_ITEM: ocp.args.StandardRestore(_abstract(template))}
            ),
        )[STATE_ITEM]
        return _rewrap_keys(restored, template)

    def saved_compatible(self, template: Any, *, step: int | None = None) -> bool:
        """Pre-restore compatibility gate: does the checkpoint's saved
        per-leaf shape list (the "_leaf_shapes" manifest save() records,
        in tree-leaves order) match the template's? Some orbax versions
        (0.7.x) silently restore a checkpoint into DIFFERENT template
        shapes instead of raising — e.g. a vocab-100 embedding into a
        vocab-140 array — which would mistrain far from the restore site.
        Checkpoints predating the manifest -> True (the restore call
        itself then decides)."""
        step = self.latest_step() if step is None else step
        if step is None:
            return False
        try:
            recorded = self._restore_meta_raw(step=step).get("_leaf_shapes")
        except Exception:
            recorded = None
        if recorded is None:
            return True
        saved = [tuple(int(d) for d in s) for s in recorded]
        want = [
            tuple(x.shape) for x in jax.tree.leaves(_abstract(template))
        ]
        return saved == want

    def restore_params(self, template: Any, *, step: int | None = None) -> Any:
        """Restore ONLY the ``params`` field of a saved TrainState/FedState.

        Every other field is skipped via ``ocp.PLACEHOLDER``, so optimizer
        moments are never materialized — restoring a C-client FedState just
        to read the (replicated) model would otherwise allocate ~3x C model
        copies. Build ``template`` with ``jax.eval_shape(lambda:
        init_state(...))`` so the template itself materializes nothing.

        NOTE: placeholder skipping is a PyTreeRestore feature, and the
        composite handler registry binds one restore-args class per item
        per manager instance — call this on a Checkpointer that has not
        already restored the full state (predict constructs its own).
        """
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        abstract = _abstract(template)
        if not hasattr(ocp, "PLACEHOLDER"):
            # Older orbax without placeholder skipping (e.g. 0.7.x):
            # restore the full abstract tree and keep only params. This
            # pays the optimizer-moment materialization the placeholder
            # path avoids — correct everywhere, memory-lean only on new
            # orbax — instead of failing the whole predict/serve restore.
            restored = self._mgr.restore(
                step,
                args=ocp.args.Composite(
                    **{STATE_ITEM: ocp.args.StandardRestore(abstract)}
                ),
            )[STATE_ITEM]
            return (
                restored["params"]
                if isinstance(restored, Mapping)
                else restored.params
            )
        masked = abstract._replace(
            **{
                f: jax.tree.map(lambda _: ocp.PLACEHOLDER, getattr(abstract, f))
                for f in abstract._fields
                if f != "params"
            }
        )
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                **{STATE_ITEM: ocp.args.PyTreeRestore(item=masked)}
            ),
        )[STATE_ITEM]
        return restored.params

    def restore_meta(self, *, step: int | None = None) -> dict:
        """The caller-supplied meta blob; internal bookkeeping keys
        (underscore-prefixed, e.g. the "_leaf_shapes" manifest) are
        stripped — they are save()'s implementation detail."""
        return {
            k: v
            for k, v in self._restore_meta_raw(step=step).items()
            if not str(k).startswith("_")
        }

    def _restore_meta_raw(self, *, step: int | None = None) -> dict:
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        try:
            return dict(
                self._mgr.restore(
                    step, args=ocp.args.Composite(**{META_ITEM: ocp.args.JsonRestore()})
                )[META_ITEM]
            )
        except (KeyError, FileNotFoundError, TypeError):
            return {}

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self) -> "Checkpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _shapes_match(restored: Any, template: Any) -> bool:
    """True when two state pytrees agree on structure and per-leaf shapes
    — the compatibility contract a warm start needs (dtype differences are
    tolerated: orbax already restores into the template's dtypes when the
    shapes agree)."""
    try:
        r_leaves, r_def = jax.tree.flatten(restored)
        t_leaves, t_def = jax.tree.flatten(template)
    except Exception:
        return False
    if r_def != t_def:
        return False
    return all(
        np.shape(r) == np.shape(t) for r, t in zip(r_leaves, t_leaves)
    )


def maybe_warm_start(directory: str, template: Any) -> tuple[Any | None, int | None]:
    """The reference's warm-start pattern (client1.py:375-377): if a
    checkpoint directory exists and holds a saved state, load it; else None.

    Returns ``(state, step)`` — callers decide whether to keep the optimizer
    state or reset it (FedConfig.reset_optimizer_each_round).

    An incompatible checkpoint (different model/vocab shapes or tree
    structure — e.g. the config changed between runs) degrades to a fresh
    start with a warning instead of aborting: warm start is an optimization,
    and the reference likewise proceeds from scratch when its ``.pth`` is
    absent.
    """
    from ..parallel.multihost import allgather_hosts

    def _agree_min(value: int) -> int:
        """Collective minimum of a host int — every warm-start decision must
        be identical on all processes, else their orbax barrier sequences
        diverge (observed as sync_global_devices name mismatches when one
        process saw the directory the other's Checkpointer just created)."""
        return int(allgather_hosts(value).min())

    if not _agree_min(int(os.path.isdir(directory))):
        return None, None
    with Checkpointer(directory) as ckpt:
        step = ckpt.latest_step()
        step_agreed = _agree_min(-1 if step is None else int(step))
        if step_agreed < 0:
            return None, None
        step = step_agreed
        if not ckpt.saved_compatible(template, step=step):
            from ..utils.logging import get_logger

            get_logger().warning(
                f"checkpoint at {directory} (step {step}) was saved under a "
                "different model shape; starting fresh"
            )
            restored: Any | None = None
        else:
            try:
                restored = ckpt.restore(template, step=step)
            except Exception as e:  # orbax raises backend-specific errors
                from ..utils.logging import get_logger

                get_logger().warning(
                    f"checkpoint at {directory} (step {step}) failed to "
                    f"restore ({type(e).__name__}: {e}); starting fresh"
                )
                restored = None
        if restored is not None and not _shapes_match(restored, template):
            # Some orbax versions restore with the CHECKPOINT's shapes
            # instead of raising when the template disagrees (e.g. the
            # default vocab grew between runs); adopting those arrays
            # would crash — or silently mistrain — far from here. Same
            # degrade-to-fresh semantics as a restore error.
            from ..utils.logging import get_logger

            get_logger().warning(
                f"checkpoint at {directory} (step {step}) has incompatible "
                "tree/leaf shapes for this config; starting fresh"
            )
            restored = None
        # The outcome must be agreed too: if any process failed to restore,
        # every process starts fresh — a split decision would desync the
        # collective training loops.
        if not _agree_min(int(restored is not None)):
            return None, None
        return restored, step
