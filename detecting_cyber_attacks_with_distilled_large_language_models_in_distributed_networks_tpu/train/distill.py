"""Knowledge distillation: teacher -> student (the "Distilled" capability).

The reference's entire relationship to distillation is consuming a
pre-distilled checkpoint (HF DistilBERT, reference client1.py:56) — it
cannot produce one. Here the DistilBERT recipe itself is a first-class
trainer: a (typically 2x-deeper) teacher's soft targets supervise the
student through a temperature-T KL term blended with hard-label CE
(``DistillConfig.alpha``), and the student can be initialized from every
other teacher layer — the published DistilBERT init.

TPU shape: one jitted step runs teacher forward (no grad, eval mode) and
student forward/backward back-to-back — both matmul stacks stay on the MXU
with no host round-trip between them. The distilled student's params feed
the ordinary :class:`~..train.engine.Trainer` / federated stack unchanged,
so "distill once, then federate the student" composes out of the box.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax

from ..config import DistillConfig, ModelConfig, TrainConfig
from ..data.pipeline import TokenizedSplit
from ..models.distilbert import DDoSClassifier
from .engine import Trainer, TrainState, apply_warmup


def distillation_loss(
    student_logits: jnp.ndarray,
    teacher_logits: jnp.ndarray,
    labels: jnp.ndarray,
    *,
    temperature: float,
    alpha: float,
) -> jnp.ndarray:
    """``alpha * T^2 * KL(teacher_T || student_T) + (1-alpha) * CE(labels)``.

    The T^2 factor keeps the soft-target gradient magnitude independent of
    temperature (Hinton et al.'s convention, which the DistilBERT recipe
    follows). Computed in fp32.
    """
    s = student_logits.astype(jnp.float32)
    t = teacher_logits.astype(jnp.float32)
    log_p_t = jax.nn.log_softmax(t / temperature, axis=-1)
    log_p_s = jax.nn.log_softmax(s / temperature, axis=-1)
    kl = (jnp.exp(log_p_t) * (log_p_t - log_p_s)).sum(axis=-1).mean()
    ce = optax.softmax_cross_entropy_with_integer_labels(s, labels).mean()
    return alpha * temperature * temperature * kl + (1.0 - alpha) * ce


def init_student_from_teacher(
    student_params: Any, teacher_params: Any, *, stride: int
) -> Any:
    """DistilBERT init: student layer ``i`` <- teacher layer ``i * stride``;
    embeddings and classifier head copied verbatim. Widths must match
    (depth-only distillation); raises on any shape mismatch so a silently
    un-initialized student can't train.
    """
    out = jax.tree.map(lambda x: x, student_params)  # structural copy
    t_enc = teacher_params["encoder"]
    s_enc = student_params["encoder"]
    n_student = sum(1 for k in s_enc if k.startswith("layer_"))
    n_teacher = sum(1 for k in t_enc if k.startswith("layer_"))
    if (n_student - 1) * stride >= n_teacher:
        raise ValueError(
            f"stride {stride} maps student layer {n_student - 1} to teacher "
            f"layer {(n_student - 1) * stride}, but teacher has {n_teacher}"
        )

    def _copy(dst, src, where):
        def _leaf(d, s):
            if jnp.shape(d) != jnp.shape(s):
                raise ValueError(
                    f"{where}: teacher leaf {jnp.shape(s)} != student "
                    f"{jnp.shape(d)} — depth-only distillation requires "
                    "matching widths"
                )
            # Materialize a distinct buffer: the student state is donated by
            # the distill step while the teacher is passed alongside it —
            # aliased buffers would poison the donation.
            return jnp.array(s)

        return jax.tree.map(_leaf, dst, src)

    new_enc = dict(out["encoder"])
    new_enc["embeddings"] = _copy(
        s_enc["embeddings"], t_enc["embeddings"], "embeddings"
    )
    for i in range(n_student):
        new_enc[f"layer_{i}"] = _copy(
            s_enc[f"layer_{i}"], t_enc[f"layer_{i * stride}"], f"layer_{i}"
        )
    out = dict(out)
    out["encoder"] = new_enc
    out["classifier"] = _copy(
        student_params["classifier"], teacher_params["classifier"], "classifier"
    )
    return out


class DistillTrainer(Trainer):
    """Student trainer whose step distills from a frozen teacher.

    Inherits init/eval/reporting from :class:`Trainer`; only the train step
    differs (teacher forward + KD loss instead of plain CE).
    """

    def __init__(
        self,
        student_cfg: ModelConfig,
        teacher_cfg: ModelConfig,
        train_cfg: TrainConfig,
        distill_cfg: DistillConfig,
        *,
        pad_id: int = 0,
    ):
        super().__init__(student_cfg, train_cfg, pad_id=pad_id)
        if teacher_cfg.dim != student_cfg.dim:
            raise ValueError(
                f"teacher dim {teacher_cfg.dim} != student dim "
                f"{student_cfg.dim}: depth-only distillation"
            )
        self.teacher_cfg = teacher_cfg
        self.distill_cfg = distill_cfg
        self.teacher_model = DDoSClassifier(teacher_cfg)
        self.distill_step = self._make_distill_step()

    def _make_distill_step(self):
        model, teacher = self.model, self.teacher_model
        dcfg = self.distill_cfg

        @partial(jax.jit, donate_argnums=(0,))
        def step(state: TrainState, teacher_params, batch):
            step_rng = jax.random.fold_in(state.rng, state.step)
            # Teacher: eval mode, no grad — soft targets only.
            t_logits = jax.lax.stop_gradient(
                teacher.apply(
                    {"params": teacher_params},
                    batch["input_ids"],
                    batch["attention_mask"],
                    True,
                )
            )

            def loss_fn(p):
                s_logits = model.apply(
                    {"params": p},
                    batch["input_ids"],
                    batch["attention_mask"],
                    False,
                    rngs={"dropout": step_rng},
                )
                return distillation_loss(
                    s_logits,
                    t_logits,
                    batch["labels"],
                    temperature=dcfg.temperature,
                    alpha=dcfg.alpha,
                )

            loss, grads = jax.value_and_grad(loss_fn)(state.params)
            updates, opt_state = self.optimizer.update(
                grads, state.opt_state, state.params
            )
            updates = apply_warmup(updates, state.step, self.train_cfg.warmup_steps)
            params = optax.apply_updates(state.params, updates)
            return TrainState(params, opt_state, state.step + 1, state.rng), loss

        return step

    def init_student_state(
        self, teacher_params: Any, seed: int | None = None
    ) -> TrainState:
        """Fresh student state, layer-initialized from the teacher when
        ``DistillConfig.init_from_teacher``. The stride is
        ``teacher_layers // student_layers`` (floored — non-divisible depths
        take the first strided layers, e.g. 5 -> 2 copies teacher layers
        0 and 2)."""
        state = self.init_state(seed=seed)
        if not self.distill_cfg.init_from_teacher:
            return state
        stride = max(1, self.teacher_cfg.n_layers // self.model_cfg.n_layers)
        params = init_student_from_teacher(
            state.params, teacher_params, stride=stride
        )
        return state._replace(params=params, opt_state=self.optimizer.init(params))

    def distill(
        self,
        state: TrainState,
        teacher_params: Any,
        split: TokenizedSplit,
        *,
        batch_size: int = 16,
        epochs: int | None = None,
        epoch_offset: int = 0,
        tag: str = "",
    ) -> tuple[TrainState, list[float]]:
        """KD epochs over the split — rides ``Trainer._fit_loop`` (same
        shuffle decorrelation via ``epoch_offset`` for multi-round drivers)."""
        return self._fit_loop(
            state,
            split,
            lambda s, b: self.distill_step(s, teacher_params, b),
            batch_size=batch_size,
            epochs=epochs,
            epoch_offset=epoch_offset,
            tag=tag,
            loss_label="KD loss",
        )
