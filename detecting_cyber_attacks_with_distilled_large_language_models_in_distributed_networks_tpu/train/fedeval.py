"""Federated evaluation plumbing: eval-split stacking, the stacked
metrics loop, and the control plane's eval-gate hooks.

The reference evaluates each client separately with a host-side sklearn
pass (client1.py:118-150); here all C clients evaluate in one jitted
vmapped sweep over a padded ``[C, M, ...]`` stack, with on-device
BinaryCounts accumulation and one host sync per evaluation.

:func:`eval_gate` and :func:`reference_histogram` are the train-side
hooks the controller (control/controller.py) gates promotion on: the
gate compares a candidate's held-out metrics against the incumbent's,
and the histogram is the score-distribution fingerprint the drift
monitor later compares live serving traffic against.
"""

from __future__ import annotations

from typing import Any, Mapping, NamedTuple, Sequence

import jax.numpy as jnp
import numpy as np

from ..data.pipeline import TokenizedSplit, pad_split_to_batch
from ..ops.metrics import (
    BinaryCounts,
    ClassCounts,
    finalize_class_metrics,
    finalize_metrics,
)


def stack_eval_splits(
    splits: Sequence[TokenizedSplit],
    batch_size: int,
    pad_id: int = 0,
    *,
    target_rows: int | None = None,
) -> tuple[TokenizedSplit, np.ndarray]:
    """Pad per-client eval splits to one common ``[C, M, ...]`` stack (M a
    batch multiple) plus a ``[C, M]`` validity matrix so every real example
    is counted exactly once per client.

    ``target_rows``: minimum row count before batch-rounding — multi-host
    processes pass the GLOBAL max split length so every host agrees on M
    (and therefore on the eval batch count, which is a collective)."""
    target = max(len(s) for s in splits)
    if target_rows is not None:
        target = max(target, target_rows)
    target += (-target) % batch_size
    ids, masks, labels, valid = [], [], [], []
    for s in splits:
        padded, v = pad_split_to_batch(s, batch_size, pad_id=pad_id)
        extra = target - len(padded)
        L = padded.input_ids.shape[1]
        ids.append(
            np.concatenate([padded.input_ids, np.full((extra, L), pad_id, np.int32)])
        )
        masks.append(
            np.concatenate([padded.attention_mask, np.zeros((extra, L), np.int32)])
        )
        labels.append(np.concatenate([padded.labels, np.zeros(extra, np.int32)]))
        valid.append(np.concatenate([v, np.zeros(extra, np.int32)]))
    return (
        TokenizedSplit(np.stack(ids), np.stack(masks), np.stack(labels)),
        np.stack(valid),
    )


class PreparedEval(NamedTuple):
    """Stacked eval splits, padded once and reused across rounds. ROC/PR
    labels come from the stacked arrays' valid rows (padding appends, so
    the valid subsequence preserves split order)."""

    stacked: TokenizedSplit  # [C, M, ...] arrays, M a batch multiple
    valid: np.ndarray  # [C, M] 0/1
    batch_size: int


def evaluate_stacked(
    trainer,
    stacked_params: Any,
    prepared: PreparedEval,
    *,
    collect_probs: bool = False,
) -> list[dict]:
    """Per-client metrics dicts (reference five-metric schema) from one
    sweep of the trainer's jitted eval step over a prepared stack."""
    stacked, valid, bs = prepared.stacked, prepared.valid, prepared.batch_size
    C = trainer.C
    M = stacked.labels.shape[1]
    # Accumulate the stacked [C] counts on device; one host sync after
    # the loop (per-batch np.asarray would block async dispatch). The
    # counts type follows the head width (BinaryCounts for K=2,
    # ClassCounts for K>2 — eval_counts' static branch).
    totals: BinaryCounts | ClassCounts | None = None
    probs_dev = []
    for i in range(M // bs):
        sl = slice(i * bs, (i + 1) * bs)
        fed = trainer._feed(
            {
                "input_ids": stacked.input_ids[:, sl],
                "attention_mask": stacked.attention_mask[:, sl],
                "labels": stacked.labels[:, sl],
                "valid": valid[:, sl],
            }
        )
        batch = {k: fed[k] for k in ("input_ids", "attention_mask", "labels")}
        counts, probs = trainer.eval_step(stacked_params, batch, fed["valid"])
        totals = counts if totals is None else totals + counts
        if collect_probs:
            probs_dev.append(probs)
    host = (
        trainer._host(totals)
        if totals is not None
        else BinaryCounts(*(np.zeros(C, np.float32) for _ in BinaryCounts._fields))
    )
    out = []
    all_probs = None
    labels_g, valid_g = stacked.labels, valid
    if probs_dev:
        # Probs accumulate as GLOBAL [C, bs] device arrays (the eval
        # step's output sharding); _host replicates across processes
        # first, so every host sees every client's probabilities.
        all_probs = np.asarray(
            trainer._host(jnp.concatenate(probs_dev, axis=1))
        )
        if trainer.P > 1:
            # The host-side labels/validity cover only LOCAL clients;
            # gather them process-major (the global client order).
            from jax.experimental import multihost_utils

            M_pad = stacked.labels.shape[1]
            labels_g = np.asarray(
                multihost_utils.process_allgather(stacked.labels)
            ).reshape(-1, M_pad)
            valid_g = np.asarray(
                multihost_utils.process_allgather(valid)
            ).reshape(-1, M_pad)
    for c in range(C):
        client_counts = type(host)(*(v[c] for v in host))
        m = (
            finalize_class_metrics(client_counts)
            if isinstance(client_counts, ClassCounts)
            else finalize_metrics(client_counts)
        )
        if collect_probs and all_probs is not None:
            # Padding appends rows, so the valid-row subsequence IS the
            # original split order (pad_split_to_batch/stack_eval_splits).
            mask_c = valid_g[c, : all_probs.shape[1]] == 1
            m["probs"] = all_probs[c][mask_c]
            m["labels"] = labels_g[c][mask_c]
        out.append(m)
    return out


# ----------------------------------------------------- control-plane hooks
def reference_histogram(probs: Any, *, bins: int = 10) -> np.ndarray:
    """Score-distribution fingerprint of a held-out evaluation: integer
    counts of P(attack) over ``bins`` equal buckets spanning [0, 1].

    Recorded in the registry manifest at artifact creation; once the
    artifact is promoted, the drift monitor (control/drift.py) compares
    live serving-score histograms (the serving tier exports the SAME
    binning, serving/server.py) against this reference — a shift says the
    traffic no longer looks like what the model was validated on."""
    p = np.clip(np.asarray(probs, np.float64).ravel(), 0.0, 1.0)
    counts, _ = np.histogram(p, bins=int(bins), range=(0.0, 1.0))
    return counts.astype(np.int64)


def eval_gate(
    candidate: Mapping[str, Any],
    incumbent: Mapping[str, Any] | None,
    *,
    metric: str = "Accuracy",
    min_delta: float = 0.0,
) -> tuple[bool, str]:
    """The promotion gate: may ``candidate`` replace ``incumbent``?

    Returns ``(ok, reason)``. A candidate whose gate metric is missing or
    non-finite NEVER passes — a corrupted aggregate (NaN params) shows up
    exactly there, and "can't evaluate" must fail closed, not promote.
    With no incumbent (bootstrap) any finite candidate passes. Otherwise
    the candidate must score at least ``incumbent[metric] - min_delta``
    (metrics here are higher-is-better, the reference's five-metric
    schema minus Loss — gate on Loss is not supported)."""
    try:
        cand = float(candidate[metric])
    except (KeyError, TypeError, ValueError):
        return False, f"candidate has no finite {metric!r}"
    if not np.isfinite(cand):
        return False, f"candidate {metric}={cand} is not finite"
    if incumbent is None:
        return True, f"bootstrap: no incumbent ({metric} {cand:.4f})"
    try:
        inc = float(incumbent[metric])
    except (KeyError, TypeError, ValueError):
        # An incumbent with no recorded metric cannot anchor a comparison;
        # treat it like bootstrap rather than blocking every promotion.
        return True, f"incumbent has no {metric!r}; promoting {cand:.4f}"
    if not np.isfinite(inc):
        return True, f"incumbent {metric} not finite; promoting {cand:.4f}"
    if cand >= inc - float(min_delta):
        return True, f"{metric} {cand:.4f} >= incumbent {inc:.4f} - {min_delta}"
    return (
        False,
        f"{metric} {cand:.4f} < incumbent {inc:.4f} - {min_delta} (regression)",
    )
