from .engine import (  # noqa: F401
    TrainState,
    Trainer,
    make_eval_step,
    make_optimizer,
    make_train_step,
)
from .federated import (  # noqa: F401
    FederatedTrainer,
    FedState,
    RoundRecord,
    federated_batches,
    stack_eval_splits,
)
