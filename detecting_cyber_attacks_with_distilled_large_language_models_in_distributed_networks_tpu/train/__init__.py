from .engine import (  # noqa: F401
    TrainState,
    Trainer,
    make_eval_step,
    make_optimizer,
    make_train_step,
)
from .client_mesh import (  # noqa: F401
    FedSeqClientTrainer,
    MeshTrainer,
    make_client_trainer,
)
from .distill import (  # noqa: F401
    DistillTrainer,
    distillation_loss,
    init_student_from_teacher,
)
from .federated import (  # noqa: F401
    FederatedTrainer,
    FedState,
    RoundRecord,
    federated_batches,
    federated_batches_ragged,
    stack_eval_splits,
)
