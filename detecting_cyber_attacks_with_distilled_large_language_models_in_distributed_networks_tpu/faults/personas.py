"""Client personas: named misbehavior profiles for the real TCP tier.

A persona combines CLIENT-side behavior (train fewer steps, sit rounds
out) with a WIRE-side fault plan (delay/throttle/reset, executed by
:class:`~.proxy.FaultProxy` against the live server). The profiles are
the heterogeneous-client regimes the reference — and the pre-PR-6 test
matrix — never exercised (TurboSVM-FL's lazy clients, arXiv:2401.12012;
the straggler/dropout rows of the communication survey,
arXiv:2405.20431):

=============  ====================================================
``honest``     the well-behaved baseline (no faults)
``lazy``       trains a fraction of the normal local steps, uploads
               on time (an under-resourced client)
``slow``       full training, but the upload crawls through a
               throttled, delayed link (the straggler)
``intermittent`` dies mid-upload on the FIRST connection of every
               exchange, then retries clean (a flapping host; the
               retry path must converge)
``stale``      sits out every second round entirely, then rejoins
               with whatever it last held (a sometimes-offline edge
               site; under DP this is the resync machinery's driver)
``flaky-net``  every connection risks a random mid-stream reset
               (seeded; never two in a row, so a retry can always
               land inside the same round), retries until the
               budget runs out
=============  ====================================================

Everything is deterministic under ``--fault-seed``: the wire plan for
client ``c``'s connection ``i`` derives from ``(fault_seed, c, i)``.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Any

from .proxy import FaultProxy, FaultSpec


@dataclass(frozen=True)
class Persona:
    """One client's behavior profile (see module docstring)."""

    name: str
    #: Fraction of the normal local-training work this client performs
    #: (lazy). Callers scale steps/epochs/rows by it, floored at one
    #: unit — a client that trains nothing uploads its init, which is
    #: legal but a different scenario.
    train_scale: float = 1.0
    #: Sit out every k-th round: round r is skipped when
    #: ``(r % skip_every) == skip_every - 1`` (stale). 0 = never.
    skip_every: int = 0
    #: Wire faults (executed by a FaultProxy; zero/negative = off).
    delay_s: float = 0.0
    throttle_bps: float = 0.0
    #: Reset the FIRST connection of every exchange after N upload
    #: bytes; the retry connection passes clean (intermittent).
    reset_first_connect_after: int = -1
    #: Per-connection probability of a random mid-stream reset, drawn
    #: deterministically from the connection rng (flaky-net).
    reset_probability: float = 0.0
    reset_window: tuple[int, int] = (512, 8192)

    def wire_faults(self) -> bool:
        """Does this persona need a FaultProxy on its connections?"""
        return (
            self.delay_s > 0.0
            or self.throttle_bps > 0.0
            or self.reset_first_connect_after >= 0
            or self.reset_probability > 0.0
        )

    def skips_round(self, round_index: int) -> bool:
        return (
            self.skip_every > 0
            and round_index % self.skip_every == self.skip_every - 1
        )

    def scaled(self, units: int) -> int:
        """Scale a work count (epochs, steps, rows) by ``train_scale``,
        floored at 1."""
        return max(1, int(round(units * self.train_scale)))


#: The registry. Wire numbers are sized for model uploads in the tens
#: of KB to tens of MB: the throttle makes `slow` a multi-second
#: straggler on the scenario runner's payloads without wedging a real
#: DistilBERT upload forever, and the reset offsets land mid-upload for
#: anything bigger than a handshake.
_PERSONAS = {
    "honest": Persona("honest"),
    "lazy": Persona("lazy", train_scale=0.25),
    "slow": Persona("slow", delay_s=0.5, throttle_bps=64_000),
    "intermittent": Persona(
        "intermittent", reset_first_connect_after=4096
    ),
    "stale": Persona("stale", skip_every=2),
    "flaky-net": Persona(
        "flaky-net", reset_probability=0.45, reset_window=(512, 8192)
    ),
}

PERSONA_NAMES = tuple(_PERSONAS)


def get_persona(name: str) -> Persona:
    try:
        return _PERSONAS[name]
    except KeyError:
        raise ValueError(
            f"unknown persona {name!r} (one of {', '.join(PERSONA_NAMES)})"
        ) from None


def persona_plan(persona: Persona):
    """The persona's per-connection FaultProxy plan: a callable
    ``(conn_index, rng) -> FaultSpec`` (rng is the proxy's deterministic
    per-connection generator)."""

    state = {"last_reset": False}

    def plan(index: int, rng: random.Random) -> FaultSpec:
        if persona.reset_first_connect_after >= 0 and index % 2 == 0:
            # Every exchange's first dial dies mid-upload; the retry
            # (the odd-indexed connection) passes clean.
            return FaultSpec(
                delay_s=persona.delay_s,
                throttle_bps=persona.throttle_bps,
                reset_after_bytes=persona.reset_first_connect_after,
            )
        if (
            persona.reset_probability > 0.0
            and not state["last_reset"]  # never two resets in a row: a
            # failed attempt's retry must be able to land inside the
            # same round (each client retry costs ~4 s of backoff +
            # mode-diagnosis peek; two in a row would slip past any
            # reasonable round deadline and smear the upload into the
            # NEXT round)
            and rng.random() < persona.reset_probability
        ):
            state["last_reset"] = True
            return FaultSpec(
                delay_s=persona.delay_s,
                throttle_bps=persona.throttle_bps,
                reset_after_bytes=rng.randrange(*persona.reset_window),
            )
        state["last_reset"] = False
        return FaultSpec(
            delay_s=persona.delay_s, throttle_bps=persona.throttle_bps
        )

    return plan


def start_persona_proxy(
    persona: Persona,
    server_host: str,
    server_port: int,
    *,
    fault_seed: Any = 0,
    client_id: int = 0,
) -> FaultProxy | None:
    """Start the persona's wire-fault proxy in front of the server (or
    return None for personas with client-side behavior only). The
    caller connects to ``(proxy.host, proxy.port)`` instead of the
    server and closes the proxy when the campaign ends.

    Caveat (documented, not hidden): behind a proxy, a client's
    connect-probe succeeds even while the *server* is still down — the
    reference-style wait-for-server probing then burns exchange retries
    instead of dial retries. Start the server first.
    """
    if not persona.wire_faults():
        return None
    return FaultProxy(
        server_host,
        server_port,
        plan=persona_plan(persona),
        seed=(fault_seed, client_id),
    )
