"""``dead-relay`` fault plan: a seeded mid-round kill of a fold-tree relay.

PR 7's hierarchical fold tree fails a whole subtree when its relay dies;
the survivable-tree work (comm/client.py fallback parents, comm/server.py
adoption + degraded rounds) exists to route around exactly that. This
module is the chaos side of the contract: a :class:`~.proxy.FaultProxy`
fronts the victim relay's subtree port, throttles the children's uploads
so they are genuinely in flight, and — once the cumulative forwarded
upload bytes cross a SEEDED threshold — tears the relay down
(``RelayAggregator.close()``, which sheds every pending child connection
as a prompt explicit failure). The children observe a mid-exchange death
and re-home to their fallback parents; the root completes the round over
the surviving subtrees.

Everything is deterministic under ``seed``: the kill threshold derives
from ``crc32(repr(("dead-relay", seed)))`` (the proxy layer's keying
convention), and the throttle makes the byte clock coarse enough that
the kill always lands mid-upload for payloads larger than the window's
upper edge.
"""

from __future__ import annotations

import random
import threading
import zlib

from ..utils.logging import get_logger
from .proxy import FaultProxy, FaultSpec

log = get_logger()


def wait_registered(server, ids, *, timeout: float) -> bool:
    """Block until every id in ``ids`` has an upload registered in
    ``server``'s current round (or ``timeout`` passes). The chaos
    harnesses' adoption gate — a deterministic ordering point that keeps
    the adoptive relay's round open through the adoption window without
    each harness poking the server's round state itself. Returns whether
    the ids all registered."""
    import time

    want = {int(i) for i in ids}
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        rnd = server._cur_rnd
        if rnd is not None:
            with rnd.lock:
                have = set(rnd.models)
            if want <= have:
                return True
        time.sleep(0.05)
    return False


class DeadRelayFault:
    """Kill ``relay`` once its children's uploads (through the fronting
    proxy) have moved a seeded number of bytes.

    Children must dial ``(fault.host, fault.port)`` instead of the relay
    itself; their fallback parents are dialed directly (the re-home path
    is already the failure path). ``close()`` tears the proxy down; the
    relay is only closed by the trigger (or by the caller)."""

    def __init__(
        self,
        relay,
        *,
        seed: int = 0,
        kill_window: tuple[int, int] = (4 << 10, 16 << 10),
        throttle_bps: float = 512_000.0,
        relay_host: str = "127.0.0.1",
        host: str = "127.0.0.1",
    ):
        if not 0 < kill_window[0] < kill_window[1]:
            raise ValueError(f"bad kill_window {kill_window}")
        rng = random.Random(
            zlib.crc32(repr(("dead-relay", seed)).encode("utf-8"))
        )
        #: The seeded byte threshold: same seed, same kill point.
        self.kill_after_bytes = rng.randrange(*kill_window)
        self.relay = relay
        self._lock = threading.Lock()
        self._forwarded = 0
        self.killed = threading.Event()
        # Throttled pass-through: the children's uploads must still be
        # in flight when the threshold crosses, or the "mid-round" kill
        # would land between rounds and test nothing.
        self.proxy = FaultProxy(
            relay_host,
            relay.port,
            plan=FaultSpec(throttle_bps=throttle_bps),
            seed=seed,
            host=host,
            on_forward=self._on_forward,
        )
        self.host, self.port = self.proxy.host, self.proxy.port

    # ------------------------------------------------------------ trigger
    def _on_forward(self, conn_index: int, nbytes: int) -> None:
        with self._lock:
            self._forwarded += nbytes
            fire = (
                self._forwarded >= self.kill_after_bytes
                and not self.killed.is_set()
            )
            if fire:
                self.killed.set()
        if fire:
            # Off the pump thread: close() joins handler state and must
            # not deadlock the very connection that pulled the trigger.
            threading.Thread(target=self._kill, daemon=True).start()

    def _kill(self) -> None:
        log.warning(
            f"[DEAD-RELAY] killing relay {self.relay.relay_id} after "
            f"{self.kill_after_bytes} forwarded upload byte(s) "
            "(seeded mid-round kill)"
        )
        self.relay.close()

    # ---------------------------------------------------------- lifecycle
    def close(self) -> None:
        self.proxy.close()

    def __enter__(self) -> "DeadRelayFault":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
