"""Fault injection for the REAL TCP tier (chaos layer, PR 6).

The reference's only failure behavior is hanging the accept loop until
timeout when a client dies (reference server.py:69-71,124-132; SURVEY
§5). This package makes failure a first-class, *deterministic* input:

* :mod:`.proxy`    — a seeded in-process TCP fault proxy that sits
                     between ``FederatedClient`` and
                     ``AggregationServer`` and injects wire-level faults
                     (delay, throttle, drop-after-N, mid-stream reset,
                     bit flips, duplicate connects) on the real socket
                     protocol, never on mocks.
* :mod:`.personas` — named client behavior profiles (``lazy``, ``slow``,
                     ``intermittent``, ``stale``, ``flaky-net``) that
                     combine client-side behavior (fewer steps, skipped
                     rounds) with a wire fault plan; wired into the CLI
                     as ``client --persona NAME --fault-seed N``.
* :mod:`.scenario` — the ``fedtpu scenario`` runner: a persona x
                     partition matrix of live loopback rounds, outcomes
                     collected from the PR 4 obs timeline (drop
                     attribution, straggler wait) with every cell's
                     aggregate crc-pinned bit-exact against a clean
                     barrier mean over the same survivor set.
* :mod:`.deadrelay` — the ``dead-relay`` fault plan (PR 14): a seeded
                     mid-round kill of a fold-tree relay behind a
                     throttling FaultProxy — the chaos driver for client
                     re-homing and degraded-root rounds.
"""

from .deadrelay import DeadRelayFault  # noqa: F401
from .personas import PERSONA_NAMES, Persona, get_persona  # noqa: F401
from .proxy import CLEAN, FaultProxy, FaultSpec  # noqa: F401
