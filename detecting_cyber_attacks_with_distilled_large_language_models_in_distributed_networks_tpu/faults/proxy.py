"""Deterministic in-process TCP fault proxy.

Sits between a ``FederatedClient`` and an ``AggregationServer`` (or any
TCP pair) on loopback and injects wire-level faults into the REAL
protocol — the frames, HMAC challenges, and stream chunks that actually
cross the socket, not mocks. Everything is seeded: connection ``i``
draws its fault plan from a generator keyed on ``(seed, i)``, so a
failing chaos run replays byte-for-byte.

Fault vocabulary (one :class:`FaultSpec` per accepted connection):

* ``delay_s``              — hold the connection before dialing upstream
                             (a slow dialer / long route).
* ``throttle_bps``         — cap client->server forwarding to N bytes/s
                             (a slow uplink; the straggler generator).
* ``drop_after_bytes``     — forward N client bytes then close both ends
                             (a crash mid-upload; the reference's hang
                             trigger).
* ``reset_after_bytes``    — forward N client bytes then hard-RST both
                             ends (SO_LINGER 0 — the WinError 10053 /
                             ECONNRESET shape from the golden logs).
* ``flip_bit_after_bytes`` — flip one bit at byte offset N of the
                             client->server stream (in-flight
                             corruption; the frame CRC must catch it).
* ``duplicate_connect``    — open and abruptly abandon a second upstream
                             connection first (the reference's
                             probe-connect-kills-server race, SURVEY §5,
                             replayed against this server).

Only the client->server direction is faulted (byte counts are upload
bytes); the reply direction forwards verbatim — a reply-side fault is
indistinguishable from a reset at the next upload, and counting both
directions would make fault offsets depend on reply timing (goodbye
determinism).
"""

from __future__ import annotations

import random
import socket
import struct
import threading
from dataclasses import dataclass, field
from typing import Any, Callable

from ..utils.logging import get_logger

log = get_logger()


@dataclass(frozen=True)
class FaultSpec:
    """One connection's fault plan; field semantics in the module
    docstring. The default is a clean pass-through."""

    delay_s: float = 0.0
    throttle_bps: float = 0.0
    drop_after_bytes: int = -1
    reset_after_bytes: int = -1
    flip_bit_after_bytes: int = -1
    duplicate_connect: bool = False

    def faulty(self) -> bool:
        return (
            self.delay_s > 0.0
            or self.throttle_bps > 0.0
            or self.drop_after_bytes >= 0
            or self.reset_after_bytes >= 0
            or self.flip_bit_after_bytes >= 0
            or self.duplicate_connect
        )


#: The clean pass-through plan.
CLEAN = FaultSpec()

#: A plan is a static spec for every connection, or a callable
#: ``(conn_index, rng) -> FaultSpec | None`` drawing per-connection
#: plans from the connection's deterministic rng (None = CLEAN).
Plan = FaultSpec | Callable[[int, random.Random], "FaultSpec | None"]

_CHUNK = 4096


def _hard_reset(sock: socket.socket) -> None:
    """Close with SO_LINGER(1, 0): the peer sees ECONNRESET, not a
    graceful FIN — the abrupt-death wire shape."""
    try:
        sock.setsockopt(
            socket.SOL_SOCKET, socket.SO_LINGER, struct.pack("ii", 1, 0)
        )
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


def _quiet_close(sock: socket.socket) -> None:
    try:
        sock.close()
    except OSError:
        pass


@dataclass
class _Conn:
    index: int
    client: socket.socket
    upstream: socket.socket | None = None
    threads: list = field(default_factory=list)
    #: Set by a fault (reset/drop) so the OTHER pump thread exits its
    #: polling recv promptly. CRITICAL for fault latency: CPython defers
    #: the OS-level close of a socket while another thread is blocked in
    #: a syscall on it — a blocking s->c recv would delay the RST until
    #: its own timeout, turning a "mid-stream reset" into a
    #: ten-seconds-later one (measured; see tests).
    dead: threading.Event = field(default_factory=threading.Event)


class FaultProxy:
    """Forwarding proxy with per-connection deterministic fault plans.

    Binds an ephemeral loopback port (``.port``); every accepted
    connection is forwarded to ``(upstream_host, upstream_port)`` under
    the plan's :class:`FaultSpec`. ``events`` records what actually
    happened (``accept``/``delay``/``throttle``/``flip``/``drop``/
    ``reset``/``duplicate-connect``/``eof``) for assertions — the chaos
    harness's own observability.
    """

    def __init__(
        self,
        upstream_host: str,
        upstream_port: int,
        *,
        plan: Plan | None = None,
        seed: Any = 0,
        host: str = "127.0.0.1",
        on_forward: Callable[[int, int], None] | None = None,
    ):
        self.upstream = (upstream_host, int(upstream_port))
        self.plan = plan
        self.seed = seed
        # Byte-progress hook ``(conn_index, chunk_bytes)`` called after
        # every upstream-forwarded chunk — the dead-relay fault plan's
        # trigger (faults/deadrelay.py kills the victim process once the
        # cumulative upload bytes cross its seeded threshold, so the
        # kill lands genuinely MID-transfer). Runs on the pump thread;
        # keep it cheap and never raise.
        self.on_forward = on_forward
        self.events: list[dict] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._conns: list[_Conn] = []
        self._n_accepted = 0
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(64)
        self._sock.settimeout(0.25)
        self.host, self.port = self._sock.getsockname()[:2]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True
        )
        self._accept_thread.start()

    # ------------------------------------------------------------ lifecycle
    def close(self) -> None:
        self._stop.set()
        _quiet_close(self._sock)
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            _quiet_close(c.client)
            if c.upstream is not None:
                _quiet_close(c.upstream)
        self._accept_thread.join(timeout=2.0)

    def __enter__(self) -> "FaultProxy":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ----------------------------------------------------------- accounting
    def _note(self, conn: int, event: str, **attrs: Any) -> None:
        rec = {"conn": conn, "event": event, **attrs}
        with self._lock:
            self.events.append(rec)

    def events_of(self, event: str) -> list[dict]:
        with self._lock:
            return [e for e in self.events if e["event"] == event]

    # ------------------------------------------------------------- plumbing
    def _spec_for(self, index: int) -> FaultSpec:
        import zlib

        # Per-connection generator keyed by crc32(repr((seed, index))):
        # stable across processes and runs (repr of ints/tuples is
        # deterministic; tuple seeding of random.Random is deprecated
        # and PYTHONHASHSEED would perturb hash()-based keys anyway).
        rng = random.Random(
            zlib.crc32(repr((self.seed, index)).encode("utf-8"))
        )
        plan = self.plan
        if plan is None:
            return CLEAN
        if callable(plan):
            return plan(index, rng) or CLEAN
        return plan

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            index = self._n_accepted
            self._n_accepted += 1
            conn = _Conn(index=index, client=client)
            with self._lock:
                self._conns.append(conn)
            t = threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            )
            conn.threads.append(t)
            t.start()

    def _handle(self, conn: _Conn) -> None:
        spec = self._spec_for(conn.index)
        self._note(
            conn.index,
            "accept",
            faulty=spec.faulty(),
            spec={
                k: v
                for k, v in vars(spec).items()
                if v not in (0.0, -1, False)
            },
        )
        if spec.delay_s > 0.0:
            self._note(conn.index, "delay", seconds=spec.delay_s)
            # Interruptible: close() mid-delay must not strand the thread.
            self._stop.wait(spec.delay_s)
        if self._stop.is_set():
            _quiet_close(conn.client)
            return
        try:
            if spec.duplicate_connect:
                # The reference's probe race, replayed: a second
                # connection that opens and dies with an RST before the
                # real exchange. A robust server shrugs it off.
                dup = socket.create_connection(self.upstream, timeout=5.0)
                self._note(conn.index, "duplicate-connect")
                _hard_reset(dup)
            conn.upstream = socket.create_connection(
                self.upstream, timeout=10.0
            )
        except OSError as e:
            self._note(conn.index, "upstream-failed", error=str(e))
            _hard_reset(conn.client)
            return
        s2c = threading.Thread(
            target=self._pump_s2c, args=(conn,), daemon=True
        )
        conn.threads.append(s2c)
        s2c.start()
        self._pump_c2s(conn, spec)
        # Let the reply direction drain (the server replies on this
        # connection up to a round deadline later), then tear down.
        s2c.join(timeout=0.5 if conn.dead.is_set() else 600.0)
        _quiet_close(conn.client)
        if conn.upstream is not None:
            _quiet_close(conn.upstream)

    def _pump_s2c(self, conn: _Conn) -> None:
        """Reply direction: verbatim forward until EOF/error. The recv
        POLLS (0.25 s timeout + the conn's dead flag) rather than
        blocking: a blocked recv would defer the fault path's
        linger-RST close until this thread's own timeout (CPython keeps
        the OS fd open while a sibling thread sits in a syscall on
        it)."""
        try:
            conn.upstream.settimeout(0.25)
        except OSError:
            return
        try:
            while not conn.dead.is_set() and not self._stop.is_set():
                try:
                    data = conn.upstream.recv(_CHUNK)
                except socket.timeout:
                    continue
                if not data:
                    break
                conn.client.sendall(data)
        except OSError:
            pass
        if not conn.dead.is_set():
            # Propagate the reply-side EOF without tearing down an
            # upload still in flight the other way.
            try:
                conn.client.shutdown(socket.SHUT_WR)
            except OSError:
                pass

    def _pump_c2s(self, conn: _Conn, spec: FaultSpec) -> None:
        """Upload direction: forward with the spec's faults applied at
        exact byte offsets (deterministic for a given plan)."""
        forwarded = 0
        throttled = False
        try:
            while True:
                # Bound reads so threshold crossings land mid-chunk at
                # worst _CHUNK bytes late — tight enough for tests to
                # pin "mid-upload".
                limit = _CHUNK
                for cut in (spec.drop_after_bytes, spec.reset_after_bytes):
                    if cut >= 0 and cut > forwarded:
                        limit = min(limit, cut - forwarded)
                data = conn.client.recv(max(1, limit))
                if not data:
                    self._note(conn.index, "eof", forwarded=forwarded)
                    try:
                        conn.upstream.shutdown(socket.SHUT_WR)
                    except OSError:
                        pass
                    return
                flip = spec.flip_bit_after_bytes
                if flip >= 0 and forwarded <= flip < forwarded + len(data):
                    buf = bytearray(data)
                    buf[flip - forwarded] ^= 0x01
                    data = bytes(buf)
                    self._note(conn.index, "flip", offset=flip)
                if spec.drop_after_bytes >= 0 and forwarded >= int(
                    spec.drop_after_bytes
                ):
                    self._note(
                        conn.index, "drop", forwarded=forwarded
                    )
                    conn.dead.set()  # unblock s2c so the close lands now
                    _quiet_close(conn.client)
                    _quiet_close(conn.upstream)
                    return
                if spec.reset_after_bytes >= 0 and forwarded >= int(
                    spec.reset_after_bytes
                ):
                    self._note(
                        conn.index, "reset", forwarded=forwarded
                    )
                    conn.dead.set()  # unblock s2c so the RST lands now
                    _hard_reset(conn.client)
                    _hard_reset(conn.upstream)
                    return
                conn.upstream.sendall(data)
                forwarded += len(data)
                if self.on_forward is not None:
                    self.on_forward(conn.index, len(data))
                if spec.throttle_bps > 0.0:
                    if not throttled:
                        throttled = True
                        self._note(
                            conn.index, "throttle", bps=spec.throttle_bps
                        )
                    # Interruptible pacing sleep.
                    if self._stop.wait(len(data) / spec.throttle_bps):
                        return
        except OSError:
            conn.dead.set()
            _quiet_close(conn.client)
            _quiet_close(conn.upstream)
