"""`fedtpu scenario`: a persona x partition matrix over LIVE loopback rounds.

Each cell of the matrix is a real federated campaign — an
``AggregationServer`` plus ``FederatedClient`` threads on loopback,
personas driving wire faults through :class:`~.proxy.FaultProxy` and
client-side misbehavior (lazy steps, skipped rounds) — never a mock.
Outcomes come from the PR 4 obs timeline (every process traces to its
own events-JSONL; the merged (trace, round) groups give contributor
sets, drop attribution, and straggler wait), and every successful
round's aggregate is pinned BIT-EXACT against the clean barrier mean
over the same survivor set (``aggregate_flat`` over the captured
survivor uploads with the same weights — the crc-pinned A/B contract
PR 5 established for streaming, extended here to arbitrary fault
mixes).

Two payload modes:

* synthetic (default) — deterministic model-shaped fp32 trees per
  (client, round); fast enough for the fast test lane and the bench
  record. Partition still matters: the server runs weighted FedAvg and
  each client's weight is its shard size, so quantity/label skew
  changes the mean.
* ``train=True`` — a tiny real model trains on the partitioned
  synthetic shards each round (serialized under a lock; jit is not
  re-entrant) and the final aggregate's held-out accuracy lands in the
  grid — the per-cell accuracy column.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from dataclasses import dataclass, field
import numpy as np

from ..comm import wire
from ..comm.client import FederatedClient
from ..comm.secure import SecureAggError
from ..comm.server import AggregationServer, aggregate_flat
from ..config import DataConfig
from ..data.partition import partition_indices, partition_manifest
from ..obs.timeline import load_spans, round_summaries
from ..obs.trace import Tracer
from ..utils.logging import get_logger
from .personas import Persona, get_persona, start_persona_proxy

log = get_logger()

#: Shared-secret for the matrix's auth cell (loopback test traffic; the
#: point is exercising the HMAC challenge path, not secrecy).
AUTH_KEY = b"fedtpu-scenario-auth"

#: Matrix partition labels -> DataConfig scheme.
PARTITION_LABELS = {
    "iid": "disjoint",
    "dirichlet": "dirichlet",
    "quantity": "quantity",
}


@dataclass(frozen=True)
class ScenarioConfig:
    num_clients: int = 3
    rounds: int = 2
    personas: tuple[str, ...] = ("lazy", "slow", "intermittent")
    partitions: tuple[str, ...] = ("iid", "dirichlet")
    dirichlet_alpha: float = 0.1
    seed: int = 0
    #: Per-client synthetic payload (model stand-in) size.
    payload_kb: int = 64
    #: Synthetic label-source rows the partitioners shard.
    data_rows: int = 480
    #: Per-round straggler deadline (the slow persona's upload must fit).
    deadline_s: float = 8.0
    #: Streamed-upload advert (0 = dense frames only).
    stream_chunk_bytes: int = 1 << 15
    #: Append one extra cell running the first persona under HMAC auth.
    auth_cell: bool = True
    #: Append the dead-relay cell: a depth-2 fold tree (two relays, one
    #: weighted root) with a seeded mid-round relay kill
    #: (faults/deadrelay.py) — the victim's clients re-home to the
    #: surviving relay and the root completes a degraded round,
    #: crc-pinned against the actual-contributor replay.
    dead_relay_cell: bool = False
    #: Train a tiny real model per client (accuracy column) instead of
    #: synthetic payloads.
    train: bool = False
    #: Server strategy specs (strategies/, ``NAME[:k=v,...]``) to APPEND
    #: as extra cells: every persona x partition pair re-runs under each
    #: non-fedavg spec, with the base cells as the fedavg baseline. The
    #: default () adds nothing — the matrix shape (and the fast lane's
    #: cell-count pin) is unchanged unless strategies are asked for.
    strategies: tuple[str, ...] = ()


@dataclass(frozen=True)
class CellSpec:
    name: str
    personas: tuple[str, ...]  # one per client
    partition: str  # "iid" | "dirichlet" | "quantity"
    auth: bool = False
    stream: bool = True
    #: Server aggregation strategy spec for this cell's root
    #: (strategies/); "fedavg" is the identity baseline.
    strategy: str = "fedavg"


@dataclass
class RoundOutcome:
    round: int
    ok: bool
    error: str | None = None
    contributors: list[int] = field(default_factory=list)
    #: Clients that never made it into the aggregate this round.
    dropped: list[int] = field(default_factory=list)
    straggler_wait_s: float = 0.0
    round_wall_s: float | None = None
    live_crc: int | None = None
    clean_crc: int | None = None
    bitexact: bool | None = None


@dataclass
class CellResult:
    spec: CellSpec
    manifest: dict
    rounds: list[RoundOutcome] = field(default_factory=list)
    stream_uploads: int = 0
    accuracy: float | None = None
    quorum: int = 0
    notes: list[str] = field(default_factory=list)

    @property
    def ok_rounds(self) -> int:
        return sum(1 for r in self.rounds if r.ok)

    @property
    def exact_rounds(self) -> int:
        return sum(1 for r in self.rounds if r.bitexact)


def build_matrix(cfg: ScenarioConfig) -> list[CellSpec]:
    """Persona x partition matrix: each cell puts ONE persona on client
    0 with an honest remainder (a quorum of honest clients is the
    contract's precondition), plus the auth cell."""
    cells = []
    for p in cfg.personas:
        for part in cfg.partitions:
            if part not in PARTITION_LABELS:
                raise ValueError(
                    f"unknown partition label {part!r} "
                    f"({'|'.join(PARTITION_LABELS)})"
                )
            cells.append(
                CellSpec(
                    name=f"{p}|{part}",
                    personas=(p,)
                    + ("honest",) * (cfg.num_clients - 1),
                    partition=part,
                )
            )
    if cfg.auth_cell and cfg.personas:
        p = cfg.personas[0]
        cells.append(
            CellSpec(
                name=f"{p}|{cfg.partitions[0]}|auth",
                personas=(p,) + ("honest",) * (cfg.num_clients - 1),
                partition=cfg.partitions[0],
                auth=True,
            )
        )
    # Strategy comparison cells: every persona x partition pair re-runs
    # under each requested non-fedavg strategy. The base cells above ARE
    # the fedavg arm (identity strategy), so a "fedavg" spec is skipped
    # rather than duplicated — the comparator reads base vs strategy
    # cells for the same (persona, partition) key.
    from ..strategies import parse_strategy

    for spec_str in cfg.strategies:
        s_name, _ = parse_strategy(spec_str)  # validates early
        if s_name == "fedavg":
            continue
        for p in cfg.personas:
            for part in cfg.partitions:
                cells.append(
                    CellSpec(
                        name=f"{p}|{part}|{spec_str}",
                        personas=(p,)
                        + ("honest",) * (cfg.num_clients - 1),
                        partition=part,
                        strategy=spec_str,
                    )
                )
    return cells


# ------------------------------------------------------------ payloads
def _partition_config(cfg: ScenarioConfig, spec: CellSpec) -> DataConfig:
    return DataConfig(
        partition=PARTITION_LABELS[spec.partition],
        data_fraction=1.0 / cfg.num_clients,
        dirichlet_alpha=cfg.dirichlet_alpha,
        seed_base=cfg.seed,
    )


def _cell_partition(
    cfg: ScenarioConfig, spec: CellSpec
) -> tuple[list[np.ndarray], np.ndarray, dict]:
    """(per-client row indices, source labels, manifest) for one cell."""
    rng = np.random.default_rng(cfg.seed)
    labels = (rng.random(cfg.data_rows) < 0.4).astype(np.int64)
    dcfg = _partition_config(cfg, spec)
    parts = partition_indices(labels, cfg.num_clients, dcfg)
    manifest = partition_manifest(
        [labels[idx] for idx in parts], cfg=dcfg, total_rows=len(labels)
    )
    return parts, labels, manifest


def _synthetic_upload(
    cfg: ScenarioConfig, spec: CellSpec, persona: Persona, cid: int, r: int
) -> dict[str, np.ndarray]:
    """Deterministic model-shaped payload for (cell, client, round):
    a pure function, so the clean-run reference regenerates survivor
    uploads exactly. The persona's ``train_scale`` scales the values
    (a lazy client's smaller local step) and the cell's partition seeds
    differ, so no two cells aggregate identical trees."""
    import zlib

    elems = max(64, int(cfg.payload_kb) * 1024 // 4 // 4)
    # crc32, not hash(): str hashing is randomized per process, and the
    # payloads must replay identically across runs (and in the clean-run
    # reference) for a given seed.
    rng = np.random.default_rng(
        [cfg.seed, zlib.crc32(spec.partition.encode()), cid, r]
    )
    scale = np.float32(persona.train_scale)
    return {
        f"w{j}": (rng.standard_normal(elems, dtype=np.float32) * scale)
        for j in range(4)
    }


# ------------------------------------------------------------ cell run
def run_cell(
    spec: CellSpec, cfg: ScenarioConfig, out_dir: str
) -> CellResult:
    """One live loopback campaign for one matrix cell."""
    workdir = os.path.join(out_dir, "cells", spec.name.replace("|", "_"))
    trace_dir = os.path.join(workdir, "traces")
    shutil.rmtree(trace_dir, ignore_errors=True)
    os.makedirs(trace_dir, exist_ok=True)
    personas = [get_persona(n) for n in spec.personas]
    parts, labels, manifest = _cell_partition(cfg, spec)
    n_samples = [max(1, len(p)) for p in parts]
    quorum = max(1, sum(1 for p in personas if p.name == "honest"))
    auth_key = AUTH_KEY if spec.auth else None
    rounds = cfg.rounds
    result = CellResult(spec=spec, manifest=manifest, quorum=quorum)

    # Captured uploads: (cid, round) -> (flat fp32 tree, n_samples) —
    # the clean-run A/B's input. Synthetic payloads are regenerable;
    # trained ones are captured at upload time.
    captured: dict[tuple[int, int], tuple[dict, float]] = {}
    aggs: list[dict | None] = [None] * rounds
    round_errors: list[str | None] = [None] * rounds
    round_done = [threading.Event() for _ in range(rounds)]
    client_errors: dict[tuple[int, int], str] = {}

    # The cell's strategy, twice over: the SERVER instance transforms
    # the live fold at finalize; the REPLAY instance is fed the clean
    # barrier means in round order, so the crc pin extends to any
    # strategy — both sides run the identical pure (prev, mean)
    # transform, and client stats stay telemetry-only by contract.
    from ..strategies import make_strategy

    replay_strategy = make_strategy(spec.strategy)
    client_mu = replay_strategy.client_mu()

    trainer = None
    shards = eval_split = None
    train_lock = threading.Lock()
    # Train mode arming barriers: local training (first-jit compile
    # included) can outlast a round deadline, so the server must not
    # START round r until every non-skipping client is about to
    # exchange — otherwise the serve loop burns its rounds against an
    # empty wire. One barrier per round: the server + that round's
    # exchangers.
    arm_barriers: list[threading.Barrier] | None = None
    if cfg.train:
        trainer, shards, eval_split = _build_training(
            cfg, parts, labels, prox_mu=client_mu
        )
        arm_barriers = [
            threading.Barrier(
                1 + sum(
                    1 for p in personas if not p.skips_round(r)
                )
            )
            for r in range(rounds)
        ]

    with AggregationServer(
        port=0,
        num_clients=cfg.num_clients,
        min_clients=quorum,
        weighted=True,
        timeout=max(30.0, cfg.deadline_s * 3),
        auth_key=auth_key,
        stream_chunk_bytes=cfg.stream_chunk_bytes if spec.stream else 0,
        strategy=spec.strategy,
        tracer=Tracer(
            os.path.join(trace_dir, "server.jsonl"), proc="server"
        ),
    ) as server:

        def serve_loop() -> None:
            for r in range(rounds):
                if arm_barriers is not None:
                    try:
                        arm_barriers[r].wait(timeout=300.0)
                    except threading.BrokenBarrierError:
                        pass  # a dead client thread; run the round anyway
                try:
                    aggs[r] = server.serve_round(deadline=cfg.deadline_s)
                except RuntimeError as e:
                    round_errors[r] = str(e)
                finally:
                    round_done[r].set()

        def client_loop(cid: int) -> None:
            persona = personas[cid]
            proxy = start_persona_proxy(
                persona,
                "127.0.0.1",
                server.port,
                fault_seed=cfg.seed,
                client_id=cid,
            )
            host, port = (
                (proxy.host, proxy.port)
                if proxy is not None
                else ("127.0.0.1", server.port)
            )
            try:
                fc = FederatedClient(
                    host,
                    port,
                    client_id=cid,
                    timeout=max(15.0, cfg.deadline_s * 2),
                    auth_key=auth_key,
                    tracer=Tracer(
                        os.path.join(trace_dir, f"client-{cid}.jsonl"),
                        proc=f"client-{cid}",
                    ),
                )
                state = None
                if trainer is not None:
                    # Under the lock: jit tracing is not re-entrant, and
                    # three threads racing the first trace is exactly
                    # the crash a chaos harness must not self-inflict.
                    with train_lock:
                        state = trainer.init_state(seed=cfg.seed)
                for r in range(rounds):
                    if persona.skips_round(r):
                        # Sitting the round out: wait until the server
                        # moved on so the NEXT upload cannot land in the
                        # skipped round's window.
                        round_done[r].wait(
                            timeout=cfg.deadline_s * 3
                        )
                        continue
                    if trainer is not None:
                        # fedtpu: allow(determinism): client-local span
                        # timestamp — timing attribution, not plan state
                        t0 = time.time()
                        tm0 = time.monotonic()
                        with train_lock:
                            shard = shards[cid]
                            sub = shard.take(
                                np.arange(persona.scaled(len(shard)))
                            )
                            state, _ = trainer.fit(
                                state, sub, batch_size=8, epochs=1,
                                epoch_offset=r,
                                tag=f"[scenario c{cid}] ",
                            )
                            upload = trainer.host_params(state)
                        fc.note_local_phase(
                            t0, time.monotonic() - tm0, client=cid
                        )
                        weight = float(len(sub))
                    else:
                        upload = _synthetic_upload(
                            cfg, spec, persona, cid, r
                        )
                        weight = float(n_samples[cid])
                    captured[(cid, r)] = (
                        {
                            k: np.asarray(v, np.float32)
                            for k, v in wire.flatten_params(
                                upload
                            ).items()
                        },
                        weight,
                    )
                    if arm_barriers is not None:
                        try:
                            arm_barriers[r].wait(timeout=300.0)
                        except threading.BrokenBarrierError:
                            pass
                    try:
                        agg = fc.exchange(
                            upload, n_samples=int(weight), max_retries=4
                        )
                    except (
                        ConnectionError,
                        OSError,
                        SecureAggError,
                        wire.WireError,
                    ) as e:
                        client_errors[(cid, r)] = str(e)
                        # Dropped this round; realign on the next one.
                        round_done[r].wait(timeout=cfg.deadline_s * 3)
                        continue
                    if trainer is not None:
                        with train_lock:
                            state = trainer.adopt_aggregate(state, agg)
            except Exception as e:  # last resort: a silently dead
                # client thread reads as "never arrived" in the grid,
                # hiding the harness's own bug — record it instead.
                client_errors[(cid, -1)] = f"{type(e).__name__}: {e}"
                log.warning(
                    f"[SCENARIO] client {cid} thread died: "
                    f"{type(e).__name__}: {e}"
                )
            finally:
                if proxy is not None:
                    proxy.close()

        st = threading.Thread(target=serve_loop, daemon=True)
        ct = [
            threading.Thread(target=client_loop, args=(c,), daemon=True)
            for c in range(cfg.num_clients)
        ]
        st.start()
        for t in ct:
            t.start()
        st.join(timeout=rounds * (cfg.deadline_s * 3 + 10))
        for t in ct:
            t.join(timeout=cfg.deadline_s * 3 + 10)
        result.stream_uploads = int(
            server.stream_totals["stream_uploads"]
        )

    # ------------------------------------------------ outcomes (obs)
    spans = load_spans(trace_dir=trace_dir)
    by_round = {
        b["round"]: b for b in round_summaries(spans) if b["round"] is not None
    }
    # The replay chain's previous-global: the live server transformed
    # each successful round's mean against ITS previous post-strategy
    # global, so the replay feeds refs forward the same way (FedAvg is
    # the identity and chains trivially). A round without a clean
    # reference resyncs the chain from the live aggregate — the later
    # rounds' pins stay meaningful instead of inheriting the gap.
    replay_strategy.reset()
    replay_prev: dict | None = None
    for r in range(rounds):
        b = by_round.get(r, {})
        contributors = list(b.get("contributors") or [])
        waits = [
            row.get("wait_s", 0.0)
            for row in (b.get("clients") or {}).values()
        ]
        out = RoundOutcome(
            round=r,
            ok=aggs[r] is not None,
            error=round_errors[r],
            contributors=contributors,
            dropped=sorted(
                set(range(cfg.num_clients)) - set(contributors)
            )
            if contributors or aggs[r] is not None
            else [],
            straggler_wait_s=round(max(waits, default=0.0), 4),
            round_wall_s=b.get("round_wall_s"),
        )
        if aggs[r] is not None:
            live = {
                k: np.asarray(v, np.float32) for k, v in aggs[r].items()
            }
            out.live_crc = wire.flat_crc32(live)
            missing = [c for c in contributors if (c, r) not in captured]
            if contributors and not missing:
                ref = aggregate_flat(
                    [captured[(c, r)][0] for c in contributors],
                    [captured[(c, r)][1] for c in contributors],
                )
                # Replay the strategy transform over the clean barrier
                # mean — fedavg returns it unchanged, so base cells pin
                # exactly what they always pinned.
                ref = replay_strategy.apply(replay_prev, ref, round_no=r)
                replay_prev = ref
                out.clean_crc = wire.flat_crc32(ref)
                out.bitexact = out.clean_crc == out.live_crc
            else:
                replay_prev = live  # resync the chain for later rounds
                result.notes.append(
                    f"round {r}: no clean reference "
                    f"(contributors {contributors}, missing {missing})"
                )
        result.rounds.append(out)
    if cfg.train and trainer is not None:
        final = next(
            (aggs[r] for r in reversed(range(rounds)) if aggs[r]), None
        )
        if final is not None:
            m = trainer.evaluate(
                wire.unflatten_params(
                    {k: np.asarray(v) for k, v in final.items()}
                ),
                eval_split,
                batch_size=8,
            )
            result.accuracy = round(float(m["Accuracy"]), 4)
            # Comparator surface: the final aggregate's held-out
            # accuracy, labeled by cell and strategy — what the
            # strategy sweep (and BENCH_MODE=strategy) scrapes to pin
            # the non-IID lift over the fedavg baseline cells.
            from ..obs import metrics as obs_metrics

            obs_metrics.default_registry().gauge(
                "fedtpu_round_accuracy",
                help="final-aggregate held-out accuracy per scenario "
                "cell, by server strategy",
                labels={
                    "cell": spec.name,
                    "strategy": replay_strategy.name,
                },
            ).set(result.accuracy)
    for (cid, r), err in sorted(client_errors.items()):
        result.notes.append(f"client {cid} round {r}: {err[:160]}")
    return result


def _build_training(
    cfg: ScenarioConfig, parts, labels, prox_mu: float = 0.0
):
    """Tiny-model training assets for ``train=True`` cells: per-client
    tokenized shards over the partitioned rows + a shared held-out eval
    split (the accuracy column's denominator). ``prox_mu`` > 0 makes
    every client run the FedProx local step (train/engine.py) against
    each round's adopted aggregate — the client half of a fedprox
    cell."""
    from ..config import ModelConfig, TrainConfig
    from ..data.pipeline import TokenizedSplit
    from ..train.engine import Trainer

    model = ModelConfig.tiny()
    trainer = Trainer(
        model, TrainConfig(learning_rate=1e-3, epochs_per_round=1,
                           seed=cfg.seed, log_every=0,
                           prox_mu=float(prox_mu))
    )
    rng = np.random.default_rng(cfg.seed + 1)
    L = model.max_len

    def _rows(n, lab):
        ids = rng.integers(0, model.vocab_size, (n, L)).astype(np.int32)
        # Label-correlated token bias so accuracy is learnable.
        ids[lab == 1, : L // 4] = 7
        return ids

    def _split(idx):
        idx = np.asarray(idx, int)
        if len(idx) == 0:
            idx = np.arange(8)
        lab = labels[idx].astype(np.int32)
        return TokenizedSplit(
            _rows(len(idx), lab), np.ones((len(idx), L), np.int32), lab
        )

    shards = [_split(p) for p in parts]
    ev = rng.integers(0, len(labels), 64)
    eval_split = _split(ev)
    # Warm the jit caches up front (train + eval step): the first trace
    # costs seconds, and paying it inside a round would eat the round
    # deadline for every cell's first client.
    warm = trainer.init_state(seed=cfg.seed)
    warm, _ = trainer.fit(
        warm, _split(np.arange(8)), batch_size=8, epochs=1,
        tag="[scenario warmup] ",
    )
    trainer.evaluate(
        trainer.host_params(warm), eval_split, batch_size=8
    )
    return trainer, shards, eval_split


# ------------------------------------------------------ dead-relay cell
def run_dead_relay_cell(
    cfg: ScenarioConfig, out_dir: str
) -> CellResult:
    """One live depth-2 fold-tree campaign with a seeded mid-round relay
    kill (faults/deadrelay.py): the victim relay's clients dial through
    the fault's throttling proxy, the kill lands while their uploads are
    in flight, they re-home to the surviving relay (ranked fallback
    parents), and the weighted root completes a DEGRADED round over the
    surviving subtree within its deadline. The outcome is attributed on
    the obs timeline (the re-home is a second ``wire-upload`` span on
    the re-homed client's trace) and the aggregate is crc-pinned
    bit-exact against :func:`~..comm.relay.aggregate_tree` replayed over
    the round's ACTUAL recorded (relay -> contributors) assignment."""
    from ..comm.relay import RelayAggregator, aggregate_tree
    from .deadrelay import DeadRelayFault, wait_registered

    spec = CellSpec(
        name=f"dead-relay|{cfg.partitions[0]}",
        personas=("honest",) * cfg.num_clients,
        partition=cfg.partitions[0],
    )
    workdir = os.path.join(out_dir, "cells", spec.name.replace("|", "_"))
    trace_dir = os.path.join(workdir, "traces")
    shutil.rmtree(trace_dir, ignore_errors=True)
    os.makedirs(trace_dir, exist_ok=True)
    parts, labels, manifest = _cell_partition(cfg, spec)
    n_samples = [max(1, len(p)) for p in parts]
    result = CellResult(spec=spec, manifest=manifest, quorum=1)
    n = cfg.num_clients
    half = max(1, n // 2)  # clients [0, half) on the surviving relay
    victims = list(range(half, n))
    persona = get_persona("honest")
    uploads = {
        cid: _synthetic_upload(cfg, spec, persona, cid, 0)
        for cid in range(n)
    }
    timeout = max(30.0, cfg.deadline_s * 3)
    results: dict[int, dict] = {}
    errors: dict[int, str] = {}
    root_agg: list = [None]
    root_err: list = [None]
    with AggregationServer(
        port=0, num_clients=2, min_clients=1, weighted=True,
        timeout=timeout, stream_chunk_bytes=cfg.stream_chunk_bytes,
        tracer=Tracer(os.path.join(trace_dir, "root.jsonl"), proc="root"),
    ) as root:
        relays = [
            RelayAggregator(
                "127.0.0.1", 0, parent_host="127.0.0.1",
                parent_port=root.port, relay_id=r,
                num_clients=(half if r == 0 else n - half),
                timeout=timeout,
                stream_chunk_bytes=cfg.stream_chunk_bytes,
            )
            for r in range(2)
        ]
        fault = DeadRelayFault(relays[1], seed=cfg.seed)
        try:
            def root_loop() -> None:
                try:
                    root_agg[0] = root.serve_round(
                        deadline=cfg.deadline_s * 2
                    )
                except RuntimeError as e:
                    root_err[0] = str(e)

            rt = threading.Thread(target=root_loop, daemon=True)
            rt.start()
            for rel in relays:
                threading.Thread(
                    target=rel.serve, args=(1,), daemon=True
                ).start()

            def client_loop(cid: int) -> None:
                victim = cid in victims
                fc = FederatedClient(
                    fault.host if victim else "127.0.0.1",
                    fault.port if victim else relays[0].port,
                    client_id=cid,
                    timeout=timeout,
                    fallback_parents=(
                        [("127.0.0.1", relays[0].port)] if victim else None
                    ),
                    rehome_dial_budget=2.0,
                    tracer=Tracer(
                        os.path.join(trace_dir, f"client-{cid}.jsonl"),
                        proc=f"client-{cid}",
                    ),
                )
                try:
                    results[cid] = fc.exchange(
                        uploads[cid],
                        n_samples=n_samples[cid],
                        max_retries=3,
                    )
                    if fc.rehomes:
                        result.notes.append(
                            f"client {cid} rehomes: {fc.rehomes}"
                        )
                except (ConnectionError, OSError, wire.WireError) as e:
                    errors[cid] = str(e)

            vt = [
                threading.Thread(target=client_loop, args=(c,), daemon=True)
                for c in victims
            ]
            for t in vt:
                t.start()
            # The survivors' clients hold their uploads until the kill
            # landed AND the re-homed uploads registered at the adoptive
            # relay — the deterministic ordering that keeps relay 0's
            # round open through the adoption window.
            fault.killed.wait(timeout=cfg.deadline_s * 2)
            wait_registered(
                relays[0].server, victims, timeout=cfg.deadline_s * 2
            )
            st = [
                threading.Thread(target=client_loop, args=(c,), daemon=True)
                for c in range(half)
            ]
            for t in st:
                t.start()
            for t in vt + st:
                t.join(timeout=timeout)
            rt.join(timeout=timeout)
        finally:
            fault.close()
            for rel in relays:
                rel.close()
    out = RoundOutcome(
        round=0,
        ok=root_agg[0] is not None,
        error=root_err[0],
        contributors=sorted(results),
        dropped=sorted(errors),
    )
    if root_agg[0] is not None and root.last_assignment is not None:
        # The recorded assignment's groups hold CLIENT ids, which here
        # are exactly indices into the uploads list — aggregate_tree
        # replays the round's ACTUAL tree directly (dropped clients are
        # simply absent from every group).
        groups = root.last_assignment["groups"]
        ref = aggregate_tree(
            [uploads[c] for c in range(n)],
            [float(n_samples[c]) for c in range(n)],
            groups,
        )
        out.live_crc = wire.flat_crc32(
            {k: np.asarray(v, np.float32) for k, v in root_agg[0].items()}
        )
        out.clean_crc = wire.flat_crc32(ref)
        out.bitexact = out.live_crc == out.clean_crc
        result.notes.append(f"assignment: {groups}")
    result.rounds.append(out)
    # Re-home visibility: the obs timeline shows a second wire-upload
    # span (the failed attempt against the dead relay, rehome_failed=1)
    # for each victim.
    spans = load_spans(trace_dir=trace_dir)
    rehome_spans = [
        s for s in spans
        if s["span"] == "wire-upload" and s.get("rehome_failed")
    ]
    result.notes.append(
        f"rehome wire-upload spans: {len(rehome_spans)} "
        f"(victims: {victims})"
    )
    if not rehome_spans:
        result.notes.append(
            "round 0: no rehome_failed wire-upload span on the timeline "
            "(bookkeeping slip)"
        )
    return result


# ----------------------------------------------------------- reporting
def run_matrix(
    cfg: ScenarioConfig, out_dir: str
) -> tuple[list[CellResult], str]:
    """Run every cell, write ``scenario.jsonl`` + ``grid.txt`` under
    ``out_dir``, and return (results, rendered grid)."""
    os.makedirs(out_dir, exist_ok=True)
    cells = build_matrix(cfg)
    results: list[CellResult] = []
    for spec in cells:
        log.info(
            f"[SCENARIO] cell {spec.name}: personas {spec.personas} "
            f"partition {spec.partition}"
            + (" auth" if spec.auth else "")
        )
        t0 = time.monotonic()
        res = run_cell(spec, cfg, out_dir)
        log.info(
            f"[SCENARIO] cell {spec.name}: {res.ok_rounds}/{cfg.rounds} "
            f"rounds ok, {res.exact_rounds} crc-exact, "
            f"{time.monotonic() - t0:.1f}s"
        )
        results.append(res)
    if cfg.dead_relay_cell:
        log.info(
            "[SCENARIO] cell dead-relay: depth-2 tree, seeded mid-round "
            "relay kill, re-home + degraded root"
        )
        t0 = time.monotonic()
        res = run_dead_relay_cell(cfg, out_dir)
        log.info(
            f"[SCENARIO] cell {res.spec.name}: "
            f"{res.ok_rounds}/1 rounds ok, {res.exact_rounds} crc-exact, "
            f"{time.monotonic() - t0:.1f}s"
        )
        results.append(res)
    grid = comparison_grid(results, cfg)
    with open(os.path.join(out_dir, "grid.txt"), "w") as f:
        f.write(grid)
    write_jsonl(results, os.path.join(out_dir, "scenario.jsonl"))
    return results, grid


def write_jsonl(results: list[CellResult], path: str) -> str:
    with open(path, "w") as f:
        for res in results:
            f.write(json.dumps(cell_record(res)) + "\n")
    return path


def cell_record(res: CellResult) -> dict:
    return {
        "cell": res.spec.name,
        "personas": list(res.spec.personas),
        "partition": res.spec.partition,
        "auth": res.spec.auth,
        "strategy": res.spec.strategy,
        "quorum": res.quorum,
        "stream_uploads": res.stream_uploads,
        "accuracy": res.accuracy,
        "manifest": res.manifest,
        "rounds": [vars(r) for r in res.rounds],
        "notes": res.notes,
    }


def comparison_grid(
    results: list[CellResult], cfg: ScenarioConfig
) -> str:
    """The persona x partition comparison grid (one compact cell per
    campaign) plus a per-cell detail block — the human-readable face of
    ``scenario.jsonl``."""

    def _cell_text(res: CellResult) -> str:
        n = len(res.rounds)
        txt = f"ok {res.ok_rounds}/{n}"
        txt += (
            f" crc {res.exact_rounds}/{res.ok_rounds}"
            if res.ok_rounds
            else ""
        )
        wait = max(
            (r.straggler_wait_s for r in res.rounds), default=0.0
        )
        txt += f" wait {wait:.1f}s"
        dropped = sorted({c for r in res.rounds for c in r.dropped})
        if dropped:
            txt += f" drop {dropped}"
        if res.accuracy is not None:
            txt += f" acc {res.accuracy:.3f}"
        return txt

    by_key = {(r.spec.personas[0], r.spec.partition, r.spec.auth): r
              for r in results
              if not r.spec.name.startswith("dead-relay")
              and r.spec.strategy == "fedavg"}
    parts = list(cfg.partitions)
    width = 34
    lines = [
        "scenario grid (rows: persona on client 0 of "
        f"{cfg.num_clients}; cols: partition; {cfg.rounds} live rounds "
        "per cell)",
        "  " + "persona".ljust(14) + "".join(p.ljust(width) for p in parts),
    ]
    for p in cfg.personas:
        row = "  " + p.ljust(14)
        for part in parts:
            res = by_key.get((p, part, False))
            row += (_cell_text(res) if res else "-").ljust(width)
        lines.append(row)
    for res in results:
        if res.spec.auth:
            lines.append(
                "  "
                + f"{res.spec.personas[0]}+auth".ljust(14)
                + _cell_text(res).ljust(width)
                + f"({res.spec.partition})"
            )
        elif res.spec.strategy != "fedavg":
            # Strategy comparison rows: same (persona, partition) key as
            # a base cell above — read down a column to compare against
            # the fedavg arm's accuracy/crc line.
            lines.append(
                "  "
                + res.spec.personas[0].ljust(14)
                + _cell_text(res).ljust(width)
                + f"({res.spec.partition}; strategy {res.spec.strategy})"
            )
        elif res.spec.name.startswith("dead-relay"):
            lines.append(
                "  "
                + "dead-relay".ljust(14)
                + _cell_text(res).ljust(width)
                + f"({res.spec.partition}; depth-2 tree, mid-round kill, "
                "re-home)"
            )
    lines.append("")
    for res in results:
        lines.append(f"cell {res.spec.name}  quorum {res.quorum}  "
                     f"stream_uploads {res.stream_uploads}")
        for r in res.rounds:
            lines.append(
                f"  round {r.round}: "
                + ("ok" if r.ok else f"FAILED ({r.error})")
                + f"  contributors {r.contributors}"
                + (f"  dropped {r.dropped}" if r.dropped else "")
                + f"  wait {r.straggler_wait_s:.2f}s"
                + (
                    "  crc-exact"
                    if r.bitexact
                    else ("  CRC-MISMATCH" if r.bitexact is False else "")
                )
            )
        for note in res.notes:
            lines.append(f"  note: {note}")
    return "\n".join(lines) + "\n"


def contract_violations(results: list[CellResult]) -> list[str]:
    """The PR 6 robustness contract, checkable: every quorum-satisfiable
    cell's every round succeeds over survivors, and every successful
    round's aggregate is bit-exact with the clean survivor mean."""
    out = []
    for res in results:
        for r in res.rounds:
            if not r.ok:
                out.append(
                    f"{res.spec.name} round {r.round}: failed ({r.error})"
                )
            elif r.bitexact is False:
                out.append(
                    f"{res.spec.name} round {r.round}: aggregate crc "
                    f"{r.live_crc:#010x} != clean survivor mean "
                    f"{r.clean_crc:#010x}"
                )
            elif r.bitexact is None:
                out.append(
                    f"{res.spec.name} round {r.round}: no clean "
                    "reference (bookkeeping slip)"
                )
    return out
