"""Multi-host federation: jax.distributed bootstrap + global mesh + feeds.

The reference's "multi-node" story is three processes on one laptop joined
by hand-rolled TCP with a polling rendezvous (reference client1.py:276-336,
server.py:116-137). The TPU-native equivalent is the JAX runtime's own
bootstrap: every process calls :func:`initialize` (coordinator address +
process id), after which ``jax.devices()`` spans all hosts and ONE SPMD
program runs across them — FedAvg rides DCN between hosts and ICI within,
with no application-level sockets at all.

Topology: :func:`make_global_mesh` lays the ``clients`` axis process-major,
so each host holds a contiguous block of client replicas. Cross-client
collectives (the FedAvg pmean) cross DCN once per round; the per-client
``data``-axis gradient psum stays inside a host's ICI domain. Data feeding
follows the same split: each process tokenizes only its own clients' shards
(:func:`local_client_slice`) and assembles global arrays with
:func:`global_batch`.

Single-process runs degrade to the ordinary mesh/arrays — every function
here is a no-op wrapper in that case, so the federated trainer has one code
path.
"""

from __future__ import annotations

import os
from typing import Any, Mapping

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from .mesh import make_mesh


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """``jax.distributed.initialize`` with env fallbacks; returns whether a
    multi-process runtime is active afterwards.

    Env fallbacks (the standard JAX names): ``JAX_COORDINATOR_ADDRESS``,
    ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``. A ``num_processes`` of 1 (or
    nothing configured) is the single-process case: no-op, returns False.
    On TPU pods the runtime can discover everything itself — then call with
    no arguments and let ``jax.distributed.initialize()`` autodetect.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])

    # NOTE: no jax.devices()/process_count() before jax.distributed
    # initializes — any backend touch would lock in a single-process runtime.
    # jax.distributed.is_initialized is newer-JAX API; on older versions
    # the only signal is the internal global_state client handle (absent
    # or unreadable -> treat as not initialized, the safe default).
    _inited = getattr(jax.distributed, "is_initialized", None)
    if _inited is not None:
        already = _inited()
    else:
        try:
            from jax._src import distributed as _distributed

            already = _distributed.global_state.client is not None
        except Exception:
            already = False
    if already:
        return jax.process_count() > 1
    configured = (
        coordinator_address is not None
        or num_processes is not None
        or process_id is not None
    )
    if not configured:
        return False  # nothing requested: ordinary single-process run
    if num_processes == 1 and coordinator_address is None:
        return False  # explicitly single-process
    # Partial configuration (e.g. a coordinator with no process id) is
    # deliberately passed through: jax.distributed.initialize either
    # autodetects the rest (TPU pods, Slurm) or raises its own precise
    # error — silently falling back to single-process would mask a typo'd
    # launch as a working run.
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return jax.process_count() > 1


def make_global_mesh(
    clients: int = 1,
    data: int = 1,
    *,
    axis_names: tuple[str, str] = ("clients", "data"),
) -> Mesh:
    """``clients x data`` mesh over ALL processes' devices, clients-major by
    process: client c's submesh lives entirely on process
    ``c // (clients / process_count)``. Requires ``clients`` to be a
    multiple of the process count and ``clients*data`` devices total.

    Single-process: identical to :func:`..mesh.make_mesh`.
    """
    if jax.process_count() == 1:
        return make_mesh(clients, data, axis_names=axis_names)
    return Mesh(_global_grid((clients, data)), axis_names)


def _global_grid(dims: tuple[int, ...]) -> np.ndarray:
    """Process-major device grid for a clients-leading global mesh: the
    one layout/validation pipeline under :func:`make_global_mesh` and
    :func:`make_global_seq_mesh`. Client c's trailing-axes block lives
    entirely on process ``c // (clients / process_count)``: within-client
    collectives (data psum, seq ring) stay on-host; only the clients-axis
    FedAvg crosses DCN."""
    P = jax.process_count()
    clients = dims[0]
    shape = "x".join(map(str, dims))
    if clients % P:
        raise ValueError(
            f"clients={clients} must be a multiple of process_count={P} so "
            "each host owns whole client replicas (within-client axes stay "
            "on-host; only FedAvg crosses DCN)"
        )
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    need = int(np.prod(dims))
    if len(devs) != need:
        raise ValueError(
            f"global mesh {shape} needs exactly {need} devices across "
            f"{P} processes, have {len(devs)}"
        )
    per_client = need // clients
    if (clients // P) * per_client != len(devs) // P:
        raise ValueError(
            f"each process must contribute (clients/P) client blocks = "
            f"{(clients // P) * per_client} devices, has {len(devs) // P}"
        )
    grid = np.array(devs).reshape(dims)
    # Backstop the layout math (e.g. heterogeneous per-host device counts
    # that pass the average check above): no client's within-client block
    # may span processes — a cross-DCN ring/psum would silently serialize
    # on the slowest link.
    for c in range(clients):
        block_procs = {d.process_index for d in grid[c].ravel()}
        if len(block_procs) != 1:
            raise ValueError(
                f"client {c}'s within-client device block spans processes "
                f"{sorted(block_procs)}; each client must stay on one host"
            )
    return grid


def make_global_seq_mesh(
    clients: int,
    data: int,
    seq: int,
    *,
    axis_names: tuple[str, str, str] = ("clients", "data", "seq"),
) -> Mesh:
    """``clients x data x seq`` mesh over ALL processes' devices, clients
    process-major: each host owns whole client replicas, so every seq ring
    (the latency-critical ppermute loop of ring attention) and every
    data-axis gradient psum stay INSIDE one host's ICI domain — only the
    FedAvg pmean over ``clients`` crosses DCN, once per round. This is the
    flagship composition on the BASELINE north-star hardware (a v4-64:
    multi-host by definition): clients over DCN x seq ring on ICI.

    Single-process: identical to :func:`..fedseq.make_seq_mesh`.
    """
    if jax.process_count() == 1:
        from .fedseq import make_seq_mesh

        return make_seq_mesh(clients, data, seq, axis_names=axis_names)
    return Mesh(_global_grid((clients, data, seq)), axis_names)


def local_client_slice(mesh: Mesh) -> slice:
    """Which block of the stacked ``[C, ...]`` client axis this process
    feeds. With the process-major layout of :func:`make_global_mesh` /
    :func:`make_global_seq_mesh`, that is one contiguous slice. Works for
    any mesh whose FIRST axis is ``clients`` (2-axis and 3-axis alike)."""
    C = mesh.devices.shape[0]
    lead = mesh.devices.reshape(C, -1)[:, 0]
    procs = [d.process_index for d in lead]
    mine = [c for c, p in enumerate(procs) if p == jax.process_index()]
    if not mine:  # a process holding no client shards feeds nothing
        return slice(0, 0)
    lo, hi = mine[0], mine[-1] + 1
    if mine != list(range(lo, hi)):
        raise ValueError(
            "client axis is not process-contiguous; build the mesh with "
            "make_global_mesh"
        )
    return slice(lo, hi)


def global_rows(
    sharding: NamedSharding, arr: np.ndarray, num_clients: int
) -> jax.Array:
    """One global ``[C, ...]`` array from this process's local client block
    ``[C_local, ...]`` (the :func:`local_client_slice` rows). The single
    assembly primitive under :func:`global_batch` and the fedseq feed
    (train/seqfed.py), whose per-key shardings differ.

    Single-process: plain ``device_put`` (local IS global)."""
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    global_shape = (num_clients, *arr.shape[1:])
    return jax.make_array_from_process_local_data(
        sharding, np.ascontiguousarray(arr), global_shape
    )


def global_batch(
    sharding: NamedSharding, local: Mapping[str, np.ndarray], num_clients: int
) -> dict[str, jax.Array]:
    """Assemble global ``[C, ...]`` arrays from this process's local client
    block ``[C_local, ...]`` (the :func:`local_client_slice` rows)."""
    return {k: global_rows(sharding, v, num_clients) for k, v in local.items()}


def allgather_hosts(value: int) -> np.ndarray:
    """Every process's value of a host int scalar, as a numpy array.

    THE primitive for cross-host agreement (batch counts, eval row counts,
    warm-start decisions): every process must call it at the same program
    point. Single-process: the value alone, no collective."""
    if jax.process_count() == 1:
        return np.asarray([value], np.int64)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(np.int64(value)))


def global_array_from_replicated(
    sharding: NamedSharding, value: np.ndarray
) -> jax.Array:
    """Build a (possibly cross-process) sharded array from a host value that
    every process holds in full — used for initial stacked params, where all
    replicas start identical (the reference's shared-pretrained-start,
    client1.py:56)."""
    if jax.process_count() == 1:
        return jax.device_put(value, sharding)
    return jax.make_array_from_callback(
        np.shape(value), sharding, lambda idx: value[idx]
    )
