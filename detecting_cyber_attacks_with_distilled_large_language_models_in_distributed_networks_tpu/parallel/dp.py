"""Differentially private FedAvg (DP-FedAvg) as an XLA collective.

The reference ships each client's raw fp32 state dict to the server
(reference client1.py:276-295) — the aggregate leaks every client's exact
update and the wire carries unprotected model weights; it has no privacy
mechanism of any kind. Here the round boundary can run the Gaussian
mechanism of DP-FedAvg (McMahan et al., "Learning Differentially Private
Recurrent Language Models", 2018):

1. each client's round update ``delta_c = params_c - anchor`` is clipped to
   a global L2 norm of at most ``clip``,
2. the uniform mean over the ``n`` participating clients is taken,
3. Gaussian noise with std ``noise_multiplier * clip / n`` is added to the
   mean update before it is applied to the anchor and broadcast back.

Adjacency notion (what the reported epsilon means): **zeroed-contribution
adjacency with a fixed divisor** — neighboring executions differ in one
client's clipped update being replaced by the zero vector while the
divisor ``n`` stays fixed, giving L2 sensitivity ``clip / n``. This is the
McMahan et al. convention (their fixed denominator ``qW``). Under the
stricter replace-one adjacency (one client's update swapped for an
arbitrary other) the mean's sensitivity is ``2 * clip / n`` and the same
noise yields roughly 4x weaker (epsilon, delta); halve the effective
noise multiplier fed to the accountant for that conservative bound.

Everything is one jitted function over the ``[C, ...]`` stacked pytree
sharded on the ``clients`` mesh axis — the clip/mean/noise pipeline lowers
to an all-reduce on ICI exactly like plain FedAvg (parallel/fedavg.py),
with the noise generated on device from a replicated key.

``dp_epsilon`` converts (rounds, noise_multiplier) into an (epsilon, delta)
guarantee by Renyi-DP composition. With full participation it composes the
plain Gaussian mechanism; with ``sampling_rate < 1`` it uses the
subsampled-Gaussian-mechanism RDP bound (Mironov, Talwar & Zhang 2019,
integer orders), which is the privacy-amplification-tight accountant —
the plain bound stays valid under subsampling but wastes the
amplification exactly where small-cohort DP needs it. The SGM bound
assumes Poisson sampling: with ``FedConfig.participation_mode="poisson"``
(the default whenever DP is on) ``participation_mask`` draws each client
independently with probability q, so the bound's assumption holds EXACTLY;
the legacy fixed-size sampler remains available, accounted with the
standard q = cohort/C approximation (the banner says which applies).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from .mesh import FedShardings


def client_update_norms(stacked_params: Any, anchor: Any) -> jnp.ndarray:
    """Per-client global L2 norm of ``params - anchor`` across all leaves,
    shape ``[C]``. Computed in fp32 regardless of param dtype."""
    deltas = jax.tree.map(
        lambda p, a: p.astype(jnp.float32) - a.astype(jnp.float32),
        stacked_params,
        anchor,
    )
    leaves = jax.tree.leaves(deltas)
    C = leaves[0].shape[0]
    sq = sum(jnp.sum(jnp.square(d.reshape(C, -1)), axis=1) for d in leaves)
    return jnp.sqrt(sq)


def dp_fedavg(
    stacked_params: Any,
    anchor: Any,
    key: jax.Array,
    mask: jnp.ndarray | None,
    *,
    clip: float,
    noise_multiplier: float,
) -> tuple[Any, jnp.ndarray]:
    """Clipped-mean-plus-noise aggregation.

    ``anchor`` is the stacked round-start params (identical along axis 0 —
    the previous round's replicated FedAvg output). Returns the new stacked
    params (every client receives the identical noised global) and the [C]
    pre-clip update norms for observability.

    Masked-out clients (``mask`` 0/1 of shape [C]) contribute nothing and
    both the mean divisor and the noise std shrink to the survivor count,
    keeping the sensitivity bound tight for the clients that did
    participate.
    """
    leaves = jax.tree.leaves(stacked_params)
    C = leaves[0].shape[0]
    m = (
        jnp.ones((C,), jnp.float32)
        if mask is None
        else mask.astype(jnp.float32)
    )
    n = jnp.maximum(m.sum(), 1.0)

    norms = client_update_norms(stacked_params, anchor)
    # Per-client contribution factor: clip-scale * participation / n.
    factor = jnp.minimum(1.0, clip / jnp.maximum(norms, 1e-12)) * m / n
    sigma = noise_multiplier * clip / n

    flat, treedef = jax.tree.flatten(stacked_params)
    flat_anchor = jax.tree.leaves(anchor)
    out = []
    for i, (p, a) in enumerate(zip(flat, flat_anchor)):
        a32 = a.astype(jnp.float32)
        d = p.astype(jnp.float32) - a32
        fshape = (C,) + (1,) * (d.ndim - 1)
        mean = (d * factor.reshape(fshape)).sum(axis=0)
        noise = sigma * jax.random.normal(
            jax.random.fold_in(key, i), mean.shape, jnp.float32
        )
        # anchor rows are identical; broadcasting the noised mean update
        # over axis 0 IS the FedAvg broadcast back to every client.
        out.append((a32 + mean + noise).astype(p.dtype))
    return jax.tree.unflatten(treedef, out), norms


def make_dp_fedavg_step(
    shardings: FedShardings, *, clip: float, noise_multiplier: float
) -> Callable:
    """Jitted DP round boundary over the mesh: params/anchor sharded
    ``P('clients')``; key and mask replicated. The clip and noise scale are
    trace-time constants (from FedConfig) — one compilation per config."""

    @partial(
        jax.jit,
        in_shardings=(shardings.client, shardings.client, None, None),
        out_shardings=(shardings.client, None),
    )
    def step(stacked_params, anchor, key, mask):
        return dp_fedavg(
            stacked_params,
            anchor,
            key,
            mask,
            clip=clip,
            noise_multiplier=noise_multiplier,
        )

    return step


DEFAULT_RDP_ORDERS: tuple[float, ...] = tuple(
    [1.0 + x / 10.0 for x in range(1, 100)] + list(range(11, 512))
)


def sgm_rdp(alpha: int, q: float, sigma: float) -> float:
    """RDP of one subsampled-Gaussian-mechanism step at INTEGER order
    ``alpha >= 2`` (Mironov, Talwar & Zhang 2019, eq. for integer orders):

        RDP(alpha) = log( sum_{k=0..alpha} C(alpha,k) (1-q)^(alpha-k) q^k
                          * exp(k (k-1) / (2 sigma^2)) ) / (alpha - 1)

    Computed in log space (the exp(k(k-1)/2sigma^2) terms overflow float64
    near alpha ~ sigma * 50)."""
    if not (isinstance(alpha, int) or float(alpha).is_integer()) or alpha < 2:
        raise ValueError(f"sgm_rdp needs an integer order >= 2, got {alpha}")
    alpha = int(alpha)
    if not 0.0 < q <= 1.0:
        raise ValueError(f"sampling rate q={q} must be in (0, 1]")
    if q == 1.0:
        return alpha / (2.0 * sigma**2)
    log_terms = []
    log_q, log_1q = math.log(q), math.log1p(-q)
    for k in range(alpha + 1):
        log_terms.append(
            math.lgamma(alpha + 1)
            - math.lgamma(k + 1)
            - math.lgamma(alpha - k + 1)
            + (alpha - k) * log_1q
            + k * log_q
            + k * (k - 1) / (2.0 * sigma**2)
        )
    m = max(log_terms)
    log_sum = m + math.log(sum(math.exp(t - m) for t in log_terms))
    return log_sum / (alpha - 1)


def dp_epsilon(
    rounds: int,
    noise_multiplier: float,
    delta: float,
    orders: Sequence[float] = DEFAULT_RDP_ORDERS,
    *,
    sampling_rate: float = 1.0,
) -> float:
    """(epsilon, delta)-DP after ``rounds`` adaptive compositions, via
    Renyi DP: per-step RDP at order alpha composes additively over rounds,
    and conversion to approximate DP takes the minimum of
    ``R * RDP(alpha) + log(1/delta) / (alpha - 1)`` over orders.

    ``sampling_rate=1`` (full participation): the Gaussian mechanism is
    (alpha, alpha / (2 sigma^2))-RDP at every real order. With
    ``sampling_rate < 1`` (partial participation, FedConfig.participation)
    the subsampled-Gaussian bound applies at integer orders >= 2
    (:func:`sgm_rdp`) — privacy amplification by subsampling, the tight
    accounting for small cohorts.

    Client-level guarantee (the clipped unit is one client's whole round
    update). Fixed-size cohorts are accounted as Poisson sampling with
    q = participation (the standard approximation).
    """
    if rounds < 0:
        raise ValueError(f"rounds={rounds} must be >= 0")
    if not 0.0 < delta < 1.0:
        raise ValueError(f"delta={delta} must be in (0, 1)")
    if not 0.0 < sampling_rate <= 1.0:
        raise ValueError(f"sampling_rate={sampling_rate} must be in (0, 1]")
    if noise_multiplier <= 0.0:
        return math.inf
    if rounds == 0:
        return 0.0
    log_delta_inv = math.log(1.0 / delta)
    best = math.inf
    # The full-participation Gaussian bound stays valid under subsampling
    # (removing clients from a round never weakens privacy) and holds at
    # every REAL order — it wins when the optimal order is fractional
    # (< 2), where the integer-order SGM bound cannot go.
    for a in orders:
        if a <= 1.0:
            continue
        eps = rounds * a / (2.0 * noise_multiplier**2) + log_delta_inv / (
            a - 1.0
        )
        best = min(best, eps)
    if sampling_rate == 1.0:
        return best
    for a in orders:
        if a < 2.0 or not float(a).is_integer():
            continue
        eps = rounds * sgm_rdp(int(a), sampling_rate, noise_multiplier)
        eps += log_delta_inv / (a - 1.0)
        best = min(best, eps)
    return best


def dp_epsilon_both(
    rounds: int,
    noise_multiplier: float,
    delta: float,
    *,
    sampling_rate: float = 1.0,
) -> tuple[float, float]:
    """Epsilon under BOTH adjacency notions, same mechanism and noise:

    * zeroed-contribution (McMahan et al. fixed-divisor, sensitivity
      ``clip/n``) — the convention :func:`dp_epsilon` reports;
    * replace-one (one client's update swapped for an arbitrary other,
      sensitivity ``2*clip/n``) — the same noise is only half as many
      sigmas of the doubled sensitivity, i.e. an effective noise
      multiplier of ``noise_multiplier / 2``.

    Operators should see both: the favorable bound alone overstates the
    protection against the stricter, more common adjacency reading."""
    return (
        dp_epsilon(rounds, noise_multiplier, delta, sampling_rate=sampling_rate),
        dp_epsilon(
            rounds, noise_multiplier / 2.0, delta, sampling_rate=sampling_rate
        ),
    )
