"""Federated long-context training: one ``clients × data × seq`` mesh.

Composes the two parallelism stories that previously ran separately:

* the federated axis — stacked ``[C, ...]`` per-client params sharded over
  ``clients``, FedAvg as a collective (parallel/fedavg.py);
* sequence parallelism — the encoder forward runs inside ``shard_map``
  with the sequence dimension sharded over ``seq``, ring attention
  rotating K/V chunks by ``ppermute`` (parallel/ring_attention.py), plus
  per-client batch parallelism over ``data``.

Layout of one train step for batch ``[C, B, L]``:

* ``input_ids`` / ``attention_mask``: ``P('clients', 'data', 'seq')`` —
  every device holds one client's batch-shard of one sequence chunk;
* ``labels``: ``P('clients', 'data')``;
* params / optimizer state: ``P('clients')`` (replicated over data+seq).

The loss runs under ONE ``shard_map`` over all three axes: a local vmap
covers the device's client replicas, the model's ring path handles
shard-offset position embeddings and global-CLS pooling over ``seq``, and
a ``pmean`` over ``data`` merges batch shards. Autodiff is taken OUTSIDE
the shard_map (shard_map is transparent to it), so the ppermute ring's
reverse path and the data-axis gradient reduction come out correct by
construction instead of by hand-placed collectives.

The reference has neither axis (three laptop processes, L=128,
client1.py:27); this is the framework's "long sequences on a federated
fleet" scaling story (SURVEY.md §5 long-context + §2.11 comm backend).

Dropout note: the step runs the model deterministically — per-(client,
seq-shard) dropout-key plumbing through shard_map is future work; the
head/FFN/attention dropouts are off in this path.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..train.engine import apply_warmup
from .fedavg import stack_params


def make_fedseq_loss(
    model,
    mesh: Mesh,
    *,
    clients_axis: str = "clients",
    data_axis: str = "data",
    seq_axis: str = "seq",
) -> Callable:
    """``(stacked_params, ids [C,B,L], mask [C,B,L], labels [C,B]) -> [C]``
    per-client mean losses, computed sequence- and batch-parallel. The
    model must be built with ``attention_impl="ring"`` and
    ``ring_axis=seq_axis``."""

    def local_losses(params_l, ids_l, mask_l, labels_l):
        def one(p, ids, mask, labels):
            logits = model.apply({"params": p}, ids, mask, True)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labels
            ).mean()

        losses = jax.vmap(one)(params_l, ids_l, mask_l, labels_l)  # [C_l]
        # Merge batch shards: each data instance saw B/data rows.
        return jax.lax.pmean(losses, data_axis)

    batch_spec = P(clients_axis, data_axis, seq_axis)
    return jax.shard_map(
        local_losses,
        mesh=mesh,
        in_specs=(
            P(clients_axis),
            batch_spec,
            batch_spec,
            P(clients_axis, data_axis),
        ),
        out_specs=P(clients_axis),
    )


def make_fedseq_train_step(
    model,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    warmup_steps: int = 0,
    clients_axis: str = "clients",
    data_axis: str = "data",
    seq_axis: str = "seq",
) -> Callable:
    """Jitted ``(stacked_params, stacked_opt_state, step, batch) ->
    (params, opt_state, losses [C])`` — one lockstep local step for every
    client, sequence-parallel inside, donated buffers."""
    loss_fn = make_fedseq_loss(
        model,
        mesh,
        clients_axis=clients_axis,
        data_axis=data_axis,
        seq_axis=seq_axis,
    )
    csh = NamedSharding(mesh, P(clients_axis))
    batch_sh = NamedSharding(mesh, P(clients_axis, data_axis, seq_axis))
    labels_sh = NamedSharding(mesh, P(clients_axis, data_axis))

    @partial(
        jax.jit,
        donate_argnums=(0, 1),
        in_shardings=(
            csh,
            csh,
            None,
            {
                "input_ids": batch_sh,
                "attention_mask": batch_sh,
                "labels": labels_sh,
            },
        ),
        out_shardings=(csh, csh, None),
    )
    def step(stacked_params, opt_state, step_idx, batch):
        def total(p):
            losses = loss_fn(
                p,
                batch["input_ids"],
                batch["attention_mask"],
                batch["labels"],
            )
            # Clients are independent: d(sum)/d(params[c]) touches only
            # client c's row, so one grad call yields every per-client grad.
            return losses.sum(), losses

        (_, losses), grads = jax.value_and_grad(total, has_aux=True)(
            stacked_params
        )
        updates, opt_state = jax.vmap(optimizer.update)(
            grads, opt_state, stacked_params
        )
        updates = apply_warmup(updates, step_idx, warmup_steps)
        params = optax.apply_updates(stacked_params, updates)
        return params, opt_state, losses

    return step


def init_fedseq_state(
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    params: Any,
    num_clients: int,
    *,
    clients_axis: str = "clients",
) -> tuple[Any, Any]:
    """Stack single-model ``params`` into the ``[C, ...]`` clients-sharded
    layout (every client starts identical — the reference's shared
    pretrained start, client1.py:56) plus matching optimizer state."""
    csh = NamedSharding(mesh, P(clients_axis))
    stacked = jax.device_put(stack_params(params, num_clients), csh)
    opt_state = jax.jit(
        lambda p: jax.vmap(optimizer.init)(p),
        in_shardings=(csh,),
        out_shardings=csh,
    )(stacked)
    return stacked, opt_state
