"""Federated long-context training: one ``clients × data × seq`` mesh.

Composes the two parallelism stories that previously ran separately:

* the federated axis — stacked ``[C, ...]`` per-client params sharded over
  ``clients``, FedAvg as a collective (parallel/fedavg.py);
* sequence parallelism — the encoder forward runs inside ``shard_map``
  with the sequence dimension sharded over ``seq``, ring attention
  rotating K/V chunks by ``ppermute`` (parallel/ring_attention.py), plus
  per-client batch parallelism over ``data``.

Layout of one train step for batch ``[C, B, L]``:

* ``input_ids`` / ``attention_mask``: ``P('clients', 'data', 'seq')`` —
  every device holds one client's batch-shard of one sequence chunk;
* ``labels``: ``P('clients', 'data')``;
* params / optimizer state: ``P('clients')`` (replicated over data+seq).

The loss runs under ONE ``shard_map`` over all three axes: a local vmap
covers the device's client replicas, the model's ring path handles
shard-offset position embeddings and global-CLS pooling over ``seq``, and
a ``pmean`` over ``data`` merges batch shards. Autodiff is taken OUTSIDE
the shard_map (shard_map is transparent to it), so the ppermute ring's
reverse path and the data-axis gradient reduction come out correct by
construction instead of by hand-placed collectives.

The reference has neither axis (three laptop processes, L=128,
client1.py:27); this is the framework's "long sequences on a federated
fleet" scaling story (SURVEY.md §5 long-context + §2.11 comm backend).

Dropout: ON in this path (the reference trains with head dropout 0.3,
client1.py:57). Per-client keys enter the shard_map sharded over
``clients``; inside, the model's ring path draws hash-based masks keyed on
GLOBAL element coordinates (ops/hash_dropout.py, models/distilbert.py
``_seq_dropout``, parallel/ring_attention.py), so the sampled masks — and
therefore the training trajectory — are invariant to the seq-axis shard
count.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..train.engine import apply_warmup, prox_sq
from .fedavg import stack_params
from .mesh import shard_map


def make_seq_mesh(
    clients: int,
    data: int,
    seq: int,
    *,
    devices: list | None = None,
    axis_names: tuple[str, str, str] = ("clients", "data", "seq"),
) -> Mesh:
    """A ``clients x data x seq`` mesh — parallel/mesh.py's make_mesh with
    the third (ring attention) axis."""
    from .mesh import make_mesh

    return make_mesh(
        clients, data, seq=seq, devices=devices, axis_names=axis_names
    )


def make_fedseq_loss(
    model,
    mesh: Mesh,
    *,
    clients_axis: str = "clients",
    data_axis: str = "data",
    seq_axis: str = "seq",
    dropout: bool = False,
    prox_mu: float = 0.0,
) -> Callable:
    """``(stacked_params, ids [C,B,L], mask [C,B,L], labels [C,B][, rngs
    [C]]) -> [C]`` per-client mean losses, computed sequence- and
    batch-parallel. The model must be built with ``attention_impl="ring"``
    and ``ring_axis=seq_axis``. With ``dropout=True`` the call takes
    per-client keys (sharded over ``clients``) and runs the model
    stochastic — masks are seq-shard-invariant (module docstring).

    With ``prox_mu > 0`` (FedProx) the call takes a stacked ``anchor``
    (the round-start params, sharded over ``clients``) right after the
    params and returns ``(objective [C], task [C])``: gradients flow from
    the objective (task + mu/2 ||p - anchor||^2, the dense path's exact
    term), logs report the task loss so FedProx and FedAvg curves stay
    comparable."""

    def local_losses(params_l, *rest):
        if prox_mu > 0.0:
            anchor_l, rest = rest[0], rest[1:]
        ids_l, mask_l, labels_l, *rngs_l = rest

        def one(p, ids, mask, labels, *key):
            if dropout:
                logits = model.apply(
                    {"params": p}, ids, mask, False,
                    rngs={"dropout": key[0]},
                )
            else:
                logits = model.apply({"params": p}, ids, mask, True)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labels
            ).mean()

        losses = jax.vmap(one)(params_l, ids_l, mask_l, labels_l, *rngs_l)
        # Merge batch shards: each data instance saw B/data rows.
        task = jax.lax.pmean(losses, data_axis)
        if prox_mu == 0.0:
            return task
        # Params (and the anchor) are replicated over data/seq, so the
        # prox term needs no collective.
        sq = jax.vmap(prox_sq)(params_l, anchor_l)
        return task + 0.5 * prox_mu * sq, task

    batch_spec = P(clients_axis, data_axis, seq_axis)
    in_specs = [P(clients_axis)]
    if prox_mu > 0.0:
        in_specs.append(P(clients_axis))
    in_specs += [
        batch_spec,
        batch_spec,
        P(clients_axis, data_axis),
    ]
    if dropout:
        in_specs.append(P(clients_axis))
    return shard_map(
        local_losses,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=(
            P(clients_axis)
            if prox_mu == 0.0
            else (P(clients_axis), P(clients_axis))
        ),
    )


def make_fedseq_masked_loss(
    model,
    mesh: Mesh,
    *,
    clients_axis: str = "clients",
    data_axis: str = "data",
    seq_axis: str = "seq",
    dropout: bool = False,
    prox_mu: float = 0.0,
) -> Callable:
    """Ragged-stack variant: ``(stacked_params, ids, mask, labels, valid
    [C,B][, rngs [C]]) -> ([C] masked mean losses, [C] 0/1 had-rows)``.
    The per-client loss averages over the batch's valid rows only (global
    across data shards — per-shard sums psum'd before the divide), so a
    padded lockstep batch contributes loss 0 / has 0 exactly like the
    dense ragged path (train/fedsteps.py per_client_step_masked).

    With ``prox_mu > 0`` a stacked ``anchor`` follows the params and the
    return is ``(objective [C], task [C], has [C])`` — see
    :func:`make_fedseq_loss`."""

    def local_losses(params_l, *rest):
        if prox_mu > 0.0:
            anchor_l, rest = rest[0], rest[1:]
        ids_l, mask_l, labels_l, valid_l, *rngs_l = rest
        def one(p, ids, mask, labels, valid, *key):
            if dropout:
                logits = model.apply(
                    {"params": p}, ids, mask, False,
                    rngs={"dropout": key[0]},
                )
            else:
                logits = model.apply({"params": p}, ids, mask, True)
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels
            )
            v = valid.astype(jnp.float32)
            return (ce * v).sum(), v.sum()

        s_loss, s_cnt = jax.vmap(one)(
            params_l, ids_l, mask_l, labels_l, valid_l, *rngs_l
        )  # [C_l] per-shard sums
        s_loss = jax.lax.psum(s_loss, data_axis)
        s_cnt = jax.lax.psum(s_cnt, data_axis)
        losses = s_loss / jnp.maximum(s_cnt, 1.0)
        has = (s_cnt > 0).astype(jnp.float32)
        if prox_mu == 0.0:
            return losses, has
        sq = jax.vmap(prox_sq)(params_l, anchor_l)
        # A no-row client's objective still carries the prox term, like
        # the dense masked step; its update is gated away on `has` anyway.
        return losses + 0.5 * prox_mu * sq, losses, has

    batch_spec = P(clients_axis, data_axis, seq_axis)
    in_specs = [P(clients_axis)]
    if prox_mu > 0.0:
        in_specs.append(P(clients_axis))
    in_specs += [
        batch_spec,
        batch_spec,
        P(clients_axis, data_axis),
        P(clients_axis, data_axis),
    ]
    if dropout:
        in_specs.append(P(clients_axis))
    return shard_map(
        local_losses,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=(
            (P(clients_axis),) * (2 if prox_mu == 0.0 else 3)
        ),
    )


def make_fedseq_train_step(
    model,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    warmup_steps: int = 0,
    clients_axis: str = "clients",
    data_axis: str = "data",
    seq_axis: str = "seq",
) -> Callable:
    """Jitted ``(stacked_params, stacked_opt_state, step, batch) ->
    (params, opt_state, losses [C])`` — one lockstep local step for every
    client, sequence-parallel inside, donated buffers."""
    loss_fn = make_fedseq_loss(
        model,
        mesh,
        clients_axis=clients_axis,
        data_axis=data_axis,
        seq_axis=seq_axis,
    )
    csh = NamedSharding(mesh, P(clients_axis))
    batch_sh = NamedSharding(mesh, P(clients_axis, data_axis, seq_axis))
    labels_sh = NamedSharding(mesh, P(clients_axis, data_axis))

    @partial(
        jax.jit,
        donate_argnums=(0, 1),
        in_shardings=(
            csh,
            csh,
            None,
            {
                "input_ids": batch_sh,
                "attention_mask": batch_sh,
                "labels": labels_sh,
            },
        ),
        out_shardings=(csh, csh, None),
    )
    def step(stacked_params, opt_state, step_idx, batch):
        def total(p):
            losses = loss_fn(
                p,
                batch["input_ids"],
                batch["attention_mask"],
                batch["labels"],
            )
            # Clients are independent: d(sum)/d(params[c]) touches only
            # client c's row, so one grad call yields every per-client grad.
            return losses.sum(), losses

        (_, losses), grads = jax.value_and_grad(total, has_aux=True)(
            stacked_params
        )
        updates, opt_state = jax.vmap(optimizer.update)(
            grads, opt_state, stacked_params
        )
        updates = apply_warmup(updates, step_idx, warmup_steps)
        params = optax.apply_updates(stacked_params, updates)
        return params, opt_state, losses

    return step


def make_fedseq_packed_loss(
    model,
    mesh: Mesh,
    *,
    data_axis: str = "data",
    seq_axis: str = "seq",
    dropout: bool = False,
    prox_mu: float = 0.0,
) -> Callable:
    """ONE client's sequence-parallel loss with NO client axis and NO
    vmap — the client-packing fast path's inner program (see
    train/fedsteps.py build_packed_step for the measured rationale; the
    3-axis variant additionally drops the inner unit vmap that the
    stacked program carries even at one local client). Signature:
    ``(params, [anchor,] ids [B,L], mask [B,L], labels [B][, key]) ->
    scalar mean loss`` (``(objective, task)`` under FedProx)."""

    def local_loss(p_l, *rest):
        if prox_mu > 0.0:
            anchor_l, rest = rest[0], rest[1:]
        ids_l, mask_l, labels_l, *key_l = rest
        if dropout:
            logits = model.apply(
                {"params": p_l}, ids_l, mask_l, False,
                rngs={"dropout": key_l[0]},
            )
        else:
            logits = model.apply({"params": p_l}, ids_l, mask_l, True)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels_l
        ).mean()
        task = jax.lax.pmean(loss, data_axis)
        if prox_mu == 0.0:
            return task
        return task + 0.5 * prox_mu * prox_sq(p_l, anchor_l), task

    in_specs = [P()]
    if prox_mu > 0.0:
        in_specs.append(P())
    in_specs += [P(data_axis, seq_axis), P(data_axis, seq_axis), P(data_axis)]
    if dropout:
        in_specs.append(P())
    return shard_map(
        local_loss,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=P() if prox_mu == 0.0 else (P(), P()),
    )


class FedSeqSteps(NamedTuple):
    """FedState-compatible jitted programs for the 3-axis composition —
    the same call signatures as train/fedsteps.py's FedSteps train/eval
    members, so FederatedTrainer's fit/eval loops drive either."""

    train_step: Callable  # (FedState, batch) -> (FedState, [C] losses)
    build_ragged_step: Callable  # () -> (FedState, batch) -> (FedState, ([C], [C]))
    eval_step: Callable  # (params, batch, valid) -> (BinaryCounts [C], probs [C,B])
    # () -> per-client packed step (client-packing fast path; see
    # train/fedsteps.py build_packed_step)
    build_packed_step: Callable = None


def build_fedseq_steps(cfg, model, optimizer, mesh: Mesh) -> FedSeqSteps:
    """Step closures over a ``clients x data x seq`` mesh. Dropout is ON
    whenever the model config carries any (the reference's 0.3 head
    dropout, client1.py:57): per-client keys fold (client rng, lockstep
    step) exactly like the dense path (train/fedsteps.py), and the
    model-side masks are seq-shard-invariant (module docstring)."""
    from ..ops.metrics import binary_counts
    from ..train.fedsteps import FedState

    mcfg = model.cfg
    dropout = (
        float(mcfg.dropout) > 0.0
        or float(mcfg.head_dropout) > 0.0
        or float(mcfg.attention_dropout) > 0.0
    )
    wsteps = cfg.train.warmup_steps
    mu = float(cfg.fed.prox_mu)
    csh = NamedSharding(mesh, P("clients"))
    repl = NamedSharding(mesh, P())
    seq_sh = NamedSharding(mesh, P("clients", "data", "seq"))
    row_sh = NamedSharding(mesh, P("clients", "data"))
    state_sh = FedState(csh, csh, repl, csh, repl)

    loss = make_fedseq_loss(model, mesh, dropout=dropout, prox_mu=mu)
    batch_sh = {"input_ids": seq_sh, "attention_mask": seq_sh, "labels": row_sh}
    from ..obs.profile import default_ledger

    ledger = default_ledger()
    note_train = ledger.hook("fedseq.train_step")

    def _train_body(state: FedState, batch, anchor):
        note_train(tuple(batch["input_ids"].shape))
        keys = (
            (jax.vmap(jax.random.fold_in, in_axes=(0, None))(
                state.rngs, state.step
            ),)
            if dropout
            else ()
        )

        def total(p):
            args = (p,) if mu == 0.0 else (p, anchor)
            out = loss(
                *args, batch["input_ids"], batch["attention_mask"],
                batch["labels"], *keys,
            )
            # Clients are independent: d(sum)/d(params[c]) touches only
            # client c's row — one grad call yields every per-client grad.
            # Under FedProx the objective carries the prox term; the task
            # loss is what gets reported (dense-path parity).
            obj, task = out if mu > 0.0 else (out, out)
            return obj.sum(), task

        (_, losses), grads = jax.value_and_grad(total, has_aux=True)(
            state.params
        )
        updates, opt_state = jax.vmap(optimizer.update)(
            grads, state.opt_state, state.params
        )
        updates = apply_warmup(updates, state.step, wsteps)
        params = optax.apply_updates(state.params, updates)
        return (
            state._replace(
                params=params, opt_state=opt_state, step=state.step + 1
            ),
            losses,
        )

    if mu > 0.0:
        # FedProx signature: (state, batch, anchor) — the same contract
        # FederatedTrainer.fit_local drives on the dense path.
        train_step = partial(
            jax.jit,
            donate_argnums=(0,),
            in_shardings=(state_sh, batch_sh, csh),
            out_shardings=(state_sh, csh),
        )(_train_body)
    else:
        train_step = partial(
            jax.jit,
            donate_argnums=(0,),
            in_shardings=(state_sh, batch_sh),
            out_shardings=(state_sh, csh),
        )(lambda state, batch: _train_body(state, batch, None))
    train_step = ledger.timed("fedseq.train_step", train_step)

    ragged_batch_sh = dict(batch_sh, valid=row_sh, warmup_step=row_sh)
    masked_loss = make_fedseq_masked_loss(
        model, mesh, dropout=dropout, prox_mu=mu
    )

    def build_ragged_step():
        def ragged_body(state: FedState, batch, anchor):
            keys = (
                (jax.vmap(jax.random.fold_in, in_axes=(0, None))(
                    state.rngs, state.step
                ),)
                if dropout
                else ()
            )

            def total(p):
                args = (p,) if mu == 0.0 else (p, anchor)
                out = masked_loss(
                    *args, batch["input_ids"], batch["attention_mask"],
                    batch["labels"], batch["valid"], *keys,
                )
                obj, losses, has = out if mu > 0.0 else (out[0], *out)
                return obj.sum(), (losses, has)

            (_, (losses, has)), grads = jax.value_and_grad(
                total, has_aux=True
            )(state.params)
            updates, new_opt = jax.vmap(optimizer.update)(
                grads, state.opt_state, state.params
            )
            # Warmup rides each client's OWN executed-step count
            # (train/batches.py federated_batches_ragged), like the dense
            # ragged path.
            updates = jax.vmap(
                lambda u, s: apply_warmup(u, s, wsteps)
            )(updates, batch["warmup_step"][:, 0])
            new_params = optax.apply_updates(state.params, updates)
            gate = lambda n, o, h: jax.tree.map(  # noqa: E731
                lambda a, b: jnp.where(h, a, b), n, o
            )
            params = jax.vmap(gate)(new_params, state.params, has > 0)
            opt_state = jax.vmap(gate)(new_opt, state.opt_state, has > 0)
            return (
                state._replace(
                    params=params, opt_state=opt_state, step=state.step + 1
                ),
                (losses, has),
            )

        if mu > 0.0:
            return partial(
                jax.jit,
                donate_argnums=(0,),
                in_shardings=(state_sh, ragged_batch_sh, csh),
                out_shardings=(state_sh, (csh, csh)),
            )(ragged_body)
        return partial(
            jax.jit,
            donate_argnums=(0,),
            in_shardings=(state_sh, ragged_batch_sh),
            out_shardings=(state_sh, (csh, csh)),
        )(lambda state, batch: ragged_body(state, batch, None))

    def local_eval(params_l, ids_l, mask_l, labels_l, valid_l):
        def one(p, ids, mask, labels, valid):
            logits = model.apply({"params": p}, ids, mask, True)
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels
            )
            probs = jax.nn.softmax(logits, axis=-1)[:, 1]
            return ce, logits, probs

        ce, logits, probs = jax.vmap(one)(
            params_l, ids_l, mask_l, labels_l, valid_l
        )

        def counts_one(ce_c, logits_c, labels_c, valid_c):
            v = valid_c.astype(jnp.float32)
            # Batch-mean loss over GLOBAL valid rows: per-shard sums merged
            # over the data axis before the divide (engine.eval_counts
            # computes the same mean unsharded).
            s_loss = jax.lax.psum((ce_c * v).sum(), "data")
            s_cnt = jax.lax.psum(v.sum(), "data")
            loss_c = s_loss / jnp.maximum(s_cnt, 1.0)
            local = binary_counts(logits_c, labels_c, loss_c, valid_c)
            # Sum the count fields over data shards; loss_sum/n_batches are
            # already global (recompute them from the global mean).
            has = (s_cnt > 0).astype(jnp.float32)
            summed = jax.tree.map(lambda x: jax.lax.psum(x, "data"), local)
            return summed._replace(
                loss_sum=loss_c * has, n_batches=has
            )

        counts = jax.vmap(counts_one)(ce, logits, labels_l, valid_l)
        return counts, probs

    eval_inner = shard_map(
        local_eval,
        mesh=mesh,
        in_specs=(
            P("clients"),
            P("clients", "data", "seq"),
            P("clients", "data", "seq"),
            P("clients", "data"),
            P("clients", "data"),
        ),
        out_specs=(P("clients"), P("clients", "data")),
    )

    @partial(
        jax.jit,
        in_shardings=(csh, batch_sh, row_sh),
    )
    def eval_step(stacked_params, batch, valid):
        return eval_inner(
            stacked_params, batch["input_ids"], batch["attention_mask"],
            batch["labels"], valid,
        )

    build_packed_step = lru_cache(maxsize=1)(
        lambda: _build_fedseq_packed_step(
            model, optimizer, mesh, dropout=dropout, mu=mu, wsteps=wsteps
        )
    )

    return FedSeqSteps(
        train_step=train_step,
        build_ragged_step=build_ragged_step,
        eval_step=eval_step,
        build_packed_step=build_packed_step,
    )


def _build_fedseq_packed_step(
    model, optimizer, mesh: Mesh, *, dropout: bool, mu: float, wsteps: int
) -> Callable:
    """Jitted per-client packed fedseq step:
    ``(cstate, batch[, anchor]) -> (cstate, task)`` — the shared packed
    builder (train/fedsteps.py make_packed_step: same rng fold, Adam,
    warmup, donation as the dense path) over the 3-axis packed loss.
    Same math as the stacked 3-axis step for one client — pinned by
    tests/test_fedseq.py::test_packed_fedseq_matches_stacked."""
    from ..train.fedsteps import make_packed_step

    loss = make_fedseq_packed_loss(model, mesh, dropout=dropout, prox_mu=mu)

    def objective(p, batch, step_rng, anchor):
        keys = (step_rng,) if dropout else ()
        args = (p,) if mu == 0.0 else (p, anchor)
        out = loss(
            *args, batch["input_ids"], batch["attention_mask"],
            batch["labels"], *keys,
        )
        return out if mu > 0.0 else (out, out)

    return make_packed_step(objective, optimizer, wsteps, mu)


def init_fedseq_state(
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    params: Any,
    num_clients: int,
    *,
    clients_axis: str = "clients",
) -> tuple[Any, Any]:
    """Stack single-model ``params`` into the ``[C, ...]`` clients-sharded
    layout (every client starts identical — the reference's shared
    pretrained start, client1.py:56) plus matching optimizer state."""
    csh = NamedSharding(mesh, P(clients_axis))
    stacked = jax.device_put(stack_params(params, num_clients), csh)
    opt_state = jax.jit(
        lambda p: jax.vmap(optimizer.init)(p),
        in_shardings=(csh,),
        out_shardings=csh,
    )(stacked)
    return stacked, opt_state
