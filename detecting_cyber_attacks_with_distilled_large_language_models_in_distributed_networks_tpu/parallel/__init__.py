from .mesh import FedShardings, make_mesh  # noqa: F401
from .fedavg import fedavg, make_fedavg_step  # noqa: F401
