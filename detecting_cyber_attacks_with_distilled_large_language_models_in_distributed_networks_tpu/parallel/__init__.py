from .mesh import FedShardings, make_host_mesh, make_mesh  # noqa: F401
from .fedavg import fedavg, make_fedavg_step  # noqa: F401
from .multihost import (  # noqa: F401
    global_array_from_replicated,
    global_batch,
    initialize,
    local_client_slice,
    make_global_mesh,
)
