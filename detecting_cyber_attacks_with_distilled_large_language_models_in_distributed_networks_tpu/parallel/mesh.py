"""Device-mesh construction for federated SPMD.

The reference's "cluster" is three OS processes on one laptop joined by
hand-rolled TCP (reference server.py:116-137). Here the cluster is a
``jax.sharding.Mesh`` with two axes:

* ``clients`` — federated replicas. Each shard of this axis holds a set of
  client model replicas + their private data shards; the FedAvg collective
  rides this axis (ICI within a slice, DCN across slices).
* ``data``    — per-client batch parallelism. Gradients sync over this axis
  automatically (XLA inserts the psum when batch is sharded and params are
  replicated along it).

For multi-host TPU pods, call ``jax.distributed.initialize()`` before
building the mesh — ``jax.devices()`` then spans all hosts and the same
code scales out; this replaces the reference's socket rendezvous
(client1.py:276-336) with the TPU runtime's own bootstrap.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# ``jax.shard_map`` became public API only in newer JAX; older versions
# (e.g. 0.4.x) ship it as jax.experimental.shard_map. One compat binding
# here so every shard_map call site (fedseq, ring attention, tests) runs
# on both.
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pragma: no cover - exercised on jax<0.5 environments
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, **kw):
        # check_rep=False: the experimental version's replication checker
        # has the known scan-carry mismatch bug (jax#21945-adjacent) that
        # the ring attention scan trips; newer JAX tracks varying axes
        # properly (see ring_attention.py's vma/pcast handling) and keeps
        # the check on.
        kw.setdefault("check_rep", False)
        return _experimental_shard_map(f, **kw)


def make_mesh(
    clients: int = 1,
    data: int = 1,
    *,
    seq: int | None = None,
    devices: list | None = None,
    axis_names: tuple[str, ...] | None = None,
) -> Mesh:
    """A ``clients x data`` mesh over the first ``clients*data`` devices;
    ``seq`` adds the third (ring attention) axis for the fedseq
    composition (parallel/fedseq.py)."""
    dims = (clients, data) if seq is None else (clients, data, seq)
    if axis_names is None:
        axis_names = ("clients", "data", "seq")[: len(dims)]
    devs = list(jax.devices() if devices is None else devices)
    need = 1
    for d in dims:
        need *= d
    if len(devs) < need:
        raise ValueError(
            f"mesh {'x'.join(map(str, dims))} needs {need} devices, have "
            f"{len(devs)} (tests: jax.config.update('jax_num_cpu_devices', N))"
        )
    grid = np.array(devs[:need]).reshape(dims)
    return Mesh(grid, axis_names)


def make_host_mesh(
    data: int = 1, *, seq: int | None = None, devices: list | None = None
) -> Mesh:
    """A single-host ``data`` (optionally ``data x seq``) mesh over this
    process's LOCAL devices — the separate-process TCP client's view of its
    own chips (cli/comm.py ``client --data-parallel N [--seq-parallel M]``).

    Unlike :func:`make_mesh` (global devices, ``clients`` leading axis),
    there is no federation axis here: federation happens over the wire, and
    every local chip serves one client's batch (and sequence) shards."""
    if data < 1 or (seq is not None and seq < 1):
        raise ValueError(f"host mesh axes must be >= 1 (data={data}, seq={seq})")
    devs = list(jax.local_devices() if devices is None else devices)
    dims = (data,) if seq is None else (data, seq)
    need = data * (seq or 1)
    if len(devs) < need:
        raise ValueError(
            f"host mesh {'x'.join(map(str, dims))} needs {need} local "
            f"devices, have {len(devs)}"
        )
    grid = np.array(devs[:need]).reshape(dims)
    return Mesh(grid, ("data",) if seq is None else ("data", "seq"))


def fit_clients_axis(num_clients: int, data: int, n_devices: int) -> int:
    """Largest clients-axis size that (a) divides the logical client count
    (several replicas may stack per mesh row) and (b) fits the hardware
    alongside the ``data`` axis. Raises when even one row doesn't fit."""
    rows = max(
        (
            r
            for r in range(1, num_clients + 1)
            if num_clients % r == 0 and r * data <= n_devices
        ),
        default=None,
    )
    if rows is None:
        raise ValueError(
            f"mesh data axis {data} alone exceeds the {n_devices} available "
            "devices"
        )
    return rows


@dataclass(frozen=True)
class FedShardings:
    """The three shardings federated training needs."""

    mesh: Mesh

    @property
    def client(self) -> NamedSharding:
        """Leading axis = clients: params/opt-state stacks ``[C, ...]``."""
        return NamedSharding(self.mesh, P("clients"))

    @property
    def batch(self) -> NamedSharding:
        """``[C, B, ...]``: clients on axis 0, per-client batch on axis 1."""
        return NamedSharding(self.mesh, P("clients", "data"))

    @property
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())


# ---------------------------------------------------------------- FSDP specs
def fsdp_dim(shape: tuple[int, ...], n_shards: int) -> int | None:
    """The dimension index FSDP shards ``shape`` over ``n_shards``, or
    None when the leaf stays replicated (scalar, or no dimension divides
    the axis). Deterministic and a pure function of (shape, n_shards) —
    the SAME choice on every process/round, which is what lets the wire
    tier scatter a decoded reply leaf straight onto its shard
    (train/client_mesh.py ``reply_leaf_sink``) without a negotiated
    layout. Largest divisible dimension wins (most bytes saved per
    shard); ties break to the lowest index."""
    if n_shards <= 1:
        return None
    best: int | None = None
    for i, d in enumerate(shape):
        if d % n_shards:
            continue
        if best is None or d > shape[best]:
            best = i
    return best


def fsdp_spec(
    shape: tuple[int, ...], n_shards: int, *, axis: str = "data"
) -> P:
    """Per-leaf FSDP ``PartitionSpec``: the chosen dimension (see
    :func:`fsdp_dim`) shards over ``axis``; everything else replicates."""
    dim = fsdp_dim(tuple(int(d) for d in shape), n_shards)
    if dim is None:
        return P()
    spec = [None] * len(shape)
    spec[dim] = axis
    return P(*spec)


def fsdp_sharding(
    mesh: Mesh, shape: tuple[int, ...], *, axis: str = "data"
) -> NamedSharding:
    """``NamedSharding`` form of :func:`fsdp_spec` for ``mesh``."""
    return NamedSharding(
        mesh, fsdp_spec(shape, int(mesh.shape[axis]), axis=axis)
    )


def fsdp_tree_shardings(tree, mesh: Mesh, *, axis: str = "data"):
    """Per-leaf shard-at-rest placement for an arbitrary state pytree:
    float/int array leaves get their :func:`fsdp_spec`; scalars, PRNG
    keys, and undividable leaves replicate. Works on concrete arrays and
    on ``ShapeDtypeStruct`` templates (only ``.shape`` is read)."""
    replicated = NamedSharding(mesh, P())

    def _leaf(x):
        shape = tuple(int(d) for d in np.shape(x))
        if not shape:
            return replicated
        dtype = getattr(x, "dtype", None)
        if dtype is not None:
            try:
                ok = np.issubdtype(np.dtype(dtype), np.floating) or (
                    np.issubdtype(np.dtype(dtype), np.integer)
                )
            except TypeError:
                # Typed PRNG keys (extended dtypes np.dtype can't parse)
                # and anything exotic replicate — bytes-trivial next to
                # params/moments.
                ok = False
            if not ok:
                return replicated
        return fsdp_sharding(mesh, shape, axis=axis)

    return jax.tree.map(_leaf, tree)


def shard_template(template, mesh: Mesh, *, axis: str = "data"):
    """Attach each leaf's FSDP ``NamedSharding`` to a ``ShapeDtypeStruct``
    restore template, so a sharding-aware checkpoint restore (orbax honors
    template shardings — train/checkpoint.py ``_abstract``) scatters every
    leaf straight onto its shard: the full-size array never materializes
    on any single chip, which is the whole point of serving a model bigger
    than one chip's memory."""
    import jax

    shardings = fsdp_tree_shardings(template, mesh, axis=axis)
    return jax.tree.map(
        lambda t, s: jax.ShapeDtypeStruct(
            tuple(int(d) for d in np.shape(t)),
            getattr(t, "dtype", np.float32),
            sharding=s,
        ),
        template,
        shardings,
    )


def fsdp_gather(mesh: Mesh):
    """The gather-AT-USE callable (the ``gather=`` side of the
    ``make_packed_step`` parameterization): constrain every leaf of a
    sharded tree to replicated, so XLA inserts the all-gather inside the
    jitted program right where the weights are consumed — full-size
    weights exist only transiently, never at rest."""
    replicated = NamedSharding(mesh, P())

    def gather(tree):
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, replicated), tree
        )

    return gather


def fsdp_gather_program(tree, mesh: Mesh, *, note=None):
    """A SEPARATE jitted all-gather program: identity over ``tree`` with
    replicated ``out_shardings``, so executing it reconstructs every
    sharded leaf's exact full-size bytes on each chip.

    Why a second program instead of :func:`fsdp_gather`'s in-body
    constraint: a constraint gather splices 100+ all-gather ops into the
    consumer's HLO module, and XLA's fusion/layout choices around those
    collectives differ from the module it builds for the same math over
    replicated inputs — a data-dependent 1-ulp drift, with zero
    all-reduces or partitioned contractions in sight. The serving crc
    contract (sharded probs bit-identical to the replicated engine's,
    bench ``serve_fsdp_crc_exact``) needs the CONSUMER program compiled
    clean; splitting the gather out gives it byte-exact replicated
    inputs and an HLO module free of collectives. Gather-at-use
    semantics are unchanged — the program runs per dispatch and its
    output is dropped with the forward, so full-size weights still never
    exist at rest. The train step keeps the constraint form (its
    contract is replaying ITSELF, where one fused module is its own
    baseline).

    ``note``: optional trace-time callable (a
    ``CompileLedger.hook`` note) — runs once per compilation, so the
    caller's ledger flags a retrace of the gather program the same way
    it flags a bucket retrace."""
    replicated = NamedSharding(mesh, P())
    out = jax.tree.map(lambda _: replicated, tree)

    def _identity(t):
        if note is not None:
            note(("gather",))
        return t

    return jax.jit(_identity, out_shardings=out)


def fsdp_constrain(mesh: Mesh, *, axis: str = "data"):
    """The shard-at-rest callable (the ``constrain=`` side): pin every
    leaf of a tree back onto its :func:`fsdp_spec` shard, so step outputs
    (new params, optimizer moments, grads) land sharded instead of
    inheriting the gathered replicated layout."""

    def constrain(tree):
        shardings = fsdp_tree_shardings(tree, mesh, axis=axis)
        return jax.tree.map(
            jax.lax.with_sharding_constraint, tree, shardings
        )

    return constrain


def device_tree_bytes(tree) -> int:
    """Bytes ``tree``'s leaves occupy on ONE device (per leaf: the
    lowest-id device holding a shard of it) — the per-chip static-state
    accounting behind the FSDP bench's ``fsdp_peak_param_opt_bytes_ratio``.
    Exact (addressable-shard nbytes, not an estimate) and backend-
    independent: it works on CPU virtual devices where
    ``device.memory_stats()`` is unavailable. A replicated leaf counts
    its full size (every chip holds a copy); a sharded leaf counts one
    shard."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            total += int(getattr(leaf, "nbytes", 0))
            continue
        first = min(shards, key=lambda s: s.device.id)
        total += sum(
            int(s.data.nbytes)
            for s in shards
            if s.device.id == first.device.id
        )
    return total
