"""Ring attention: sequence-parallel blockwise attention over a mesh axis.

Long-context path: the sequence dimension is sharded over a ``seq`` mesh
axis, each device holding [B, H, L/n, D] query/key/value shards. Attention
runs in n ring steps — every device computes blockwise attention of its
local queries against the key/value chunk it currently holds, then passes
that chunk to its ring neighbor with ``jax.lax.ppermute`` (one ICI hop),
accumulating results with the online-softmax (flash) recurrence. No device
ever materializes the full [L, L] score matrix or the full K/V — memory is
O(L/n · D) per device and communication rides the ICI ring.

The reference has nothing like this (sequences are fixed 128 tokens,
reference client1.py:27); this is the framework's long-context scaling
story, composing the flash recurrence (ops/flash_attention.py) with the
mesh machinery (parallel/mesh.py).

``ring_attention`` must be called inside ``shard_map`` with ``axis_name``
bound (the model's ``attention_impl="ring"`` path assumes the whole forward
runs under one); ``ring_attention_sharded`` wraps full arrays for
standalone/tests. Everything is differentiable — ``ppermute`` and the
recurrence are standard JAX ops, so autodiff composes (gradients take the
reverse ring).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _partial_attention(q, k, v, bias, scale, drop=None):
    """Unnormalized flash statistics of local queries vs one K/V chunk.

    Returns ``(pv, m, l)``: exp-weighted values, row max, row denominator —
    enough to merge chunks with the online-softmax recurrence.

    Numerics contract: matmul INPUTS stay in the activation dtype (bf16 on
    TPU — both einsums feed the MXU half-width operands) with fp32
    accumulation via ``preferred_element_type``; scaling, softmax
    statistics and the merge recurrence run fp32. Same contract as the dot
    path (ops/attention.py) and the flash kernels
    (ops/flash_attention.py) — under fp32 activations (CPU tests) it
    degenerates to full fp32, so dot-path parity stays exact.

    ``drop = (seed, rate, b_off, q_off, k_off)`` applies attention dropout
    with a GLOBAL-coordinate hash mask (ops/hash_dropout.py) — batch rows,
    query and key positions all offset to their global indices: the pv
    numerator is masked and inverse-scaled, the denominator ``l``
    accumulates undropped weights — exactly the dot path's
    drop-after-softmax semantics (ops/attention.py:56-61) expressed in the
    online recurrence."""
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    m = s.max(axis=-1)  # [B,H,Lq]
    p = jnp.exp(s - m[..., None])
    l = p.sum(axis=-1)
    if drop is not None:
        from ..ops.hash_dropout import hash_keep_mask

        seed, rate, b_off, q_off, k_off = drop
        keep = hash_keep_mask(
            seed, p.shape, rate, offsets={0: b_off, 2: q_off, 3: k_off}
        )
        p = p * keep * (1.0 / (1.0 - rate))
    pv = jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(q.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return pv, m, l


def _merge_partial(acc, m, l, pv_i, m_i, l_i):
    """Online-softmax merge of one chunk's partial statistics into the
    running ``(acc, m, l)`` — shared by the sharded ring and the
    single-device blockwise variant so their numerics stay structurally
    identical."""
    m_new = jnp.maximum(m, m_i)
    alpha = jnp.exp(m - m_new)
    alpha_i = jnp.exp(m_i - m_new)
    acc = acc * alpha[..., None] + pv_i * alpha_i[..., None]
    l = l * alpha + l_i * alpha_i
    return acc, m_new, l


def ring_attention(
    q: jnp.ndarray,  # [B, H, Lq_local, D] — local query shard
    k: jnp.ndarray,  # [B, H, Lk_local, D] — local key shard
    v: jnp.ndarray,  # [B, H, Lk_local, D]
    bias: jnp.ndarray | None = None,  # [B, 1, 1, Lk_local] — mask for LOCAL keys
    *,
    axis_name: str = "seq",
    dropout_rate: float = 0.0,
    dropout_rng: jax.Array | None = None,
    deterministic: bool = True,
    batch_offset: jax.Array | int = 0,
) -> jnp.ndarray:
    """Sequence-parallel attention inside ``shard_map``; the key-position
    bias (when given) rotates around the ring together with its K/V chunk.

    Only key-position biases are accepted: a bias with a real query dimension
    would be applied to *other devices'* queries after the first rotation.

    Attention dropout (``dropout_rate``/``dropout_rng``): masks come from a
    hash of the GLOBAL (query, key) coordinates — each K/V chunk's global
    offset rotates around the ring alongside it — so the sampled mask is
    invariant to the seq-axis shard count (the same property the flash
    kernels' forward/backward mask regeneration relies on). The rng must be
    shard-invariant (flax ``make_rng`` keys are).
    """
    if bias is not None and (
        bias.ndim != 4 or bias.shape[1] != 1 or bias.shape[2] != 1
    ):
        raise ValueError(
            f"ring_attention supports key-position bias [B,1,1,Lk] only, "
            f"got {bias.shape}"
        )
    n = jax.lax.psum(1, axis_name)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    perm = [(i, (i + 1) % n) for i in range(n)]
    has_bias = bias is not None
    rate = float(dropout_rate) if not deterministic else 0.0
    if rate > 0.0 and dropout_rng is None:
        raise ValueError("ring attention dropout needs dropout_rng")
    lk = k.shape[2]
    if rate > 0.0:
        seed = jax.random.bits(dropout_rng, (2,), jnp.uint32)
        q_off = jax.lax.axis_index(axis_name) * q.shape[2]
    else:
        seed = q_off = None

    def merge(acc, m, l, k_c, v_c, b_c, k_off):
        drop = (
            None
            if rate == 0.0
            else (seed, rate, batch_offset, q_off, k_off)
        )
        pv_i, m_i, l_i = _partial_attention(
            q, k_c, v_c, b_c if has_bias else None, scale, drop
        )
        return _merge_partial(acc, m, l, pv_i, m_i, l_i)

    def rotate(x):
        return jax.tree.map(lambda t: jax.lax.ppermute(t, axis_name, perm), x)

    b_sz, h, lq, d = q.shape
    acc0 = jnp.zeros((b_sz, h, lq, d), jnp.float32)
    m0 = jnp.full((b_sz, h, lq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b_sz, h, lq), jnp.float32)

    # Constants enter the scan carry device-invariant but come out varying
    # over every mesh axis q varies over (the ring axis alone inside a pure
    # seq shard_map; clients/data too inside the 3-axis fedseq composition);
    # mark them varying up front so the scan carry types match.
    # (jax.typeof and the vma/pcast machinery exist only on newer JAX;
    # older versions' shard_map has no varying-axis avals, so want_vma is
    # empty there and _vary is the identity.)
    _typeof = getattr(jax, "typeof", lambda _x: None)
    want_vma = tuple(getattr(_typeof(q), "vma", ()) or ())

    def _vary(x):
        have = getattr(_typeof(x), "vma", ()) or ()
        missing = tuple(a for a in want_vma if a not in have)
        if not missing:
            return x
        return jax.lax.pcast(x, missing, to="varying")

    acc0, m0, l0 = jax.tree.map(_vary, (acc0, m0, l0))
    b0 = bias if has_bias else ()  # empty pytree: nothing rotates when no mask
    # Each chunk's global key offset rides the ring with its K/V (axis_index
    # itself must be marked varying to enter the rotating carry).
    k_off0 = _vary(jax.lax.axis_index(axis_name).astype(jnp.int32) * lk)

    def step(carry, _):
        k_c, v_c, b_c, k_off, acc, m, l = carry
        acc, m, l = merge(acc, m, l, k_c, v_c, b_c, k_off)
        return (
            rotate(k_c), rotate(v_c), rotate(b_c), rotate(k_off), acc, m, l
        ), None

    # n-1 compute+rotate steps; the final chunk is merged without the last
    # rotation (its rotated carry would be discarded — one wasted ICI hop
    # of full K/V per layer otherwise).
    (k_f, v_f, b_f, k_off_f, acc, m, l), _ = jax.lax.scan(
        step, (k, v, b0, k_off0, acc0, m0, l0), None, length=n - 1
    )
    acc, m, l = merge(acc, m, l, k_f, v_f, b_f, k_off_f)
    # -1e9 mask addends keep l > 0 even for fully masked rows (parity with
    # the dot/flash paths).
    return (acc / l[..., None]).astype(q.dtype)


def blockwise_attention_local(
    q: jnp.ndarray,  # [B, H, L, D] — full arrays, ONE device
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: jnp.ndarray | None = None,  # [B, 1, 1, L] key-position mask
    *,
    n_chunks: int = 8,
) -> jnp.ndarray:
    """The ring schedule's compute on one device: K/V split into
    ``n_chunks`` chunks merged with the same ``_partial_attention`` +
    online-softmax recurrence, ppermute hops removed. Numerically it is
    ``ring_attention`` on an ``n_chunks``-device mesh (the recurrence and
    chunk order are identical; only the transport differs), so it serves
    as (a) the single-chip benchmark proxy for the ring path's per-chunk
    math (BENCH_MODE=ring) and (b) a parity anchor against the dot path.
    Deterministic only — the dropout story lives in the sharded path."""
    b_sz, h, lq, d = q.shape
    lk = k.shape[2]
    if lk % n_chunks:
        raise ValueError(f"L={lk} must divide into n_chunks={n_chunks}")
    ck = lk // n_chunks
    scale = 1.0 / (d**0.5)
    # [n, B, H, ck, D] chunk-major stacks feed the scan.
    kc = jnp.moveaxis(k.reshape(b_sz, h, n_chunks, ck, d), 2, 0)
    vc = jnp.moveaxis(v.reshape(b_sz, h, n_chunks, ck, d), 2, 0)
    if bias is not None:
        bc = jnp.moveaxis(bias.reshape(b_sz, 1, 1, n_chunks, ck), 3, 0)
        xs = (kc, vc, bc)
    else:
        xs = (kc, vc)

    acc0 = jnp.zeros((b_sz, h, lq, d), jnp.float32)
    m0 = jnp.full((b_sz, h, lq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b_sz, h, lq), jnp.float32)

    def step(carry, chunk):
        acc, m, l = carry
        k_c, v_c = chunk[0], chunk[1]
        b_c = chunk[2] if bias is not None else None
        pv_i, m_i, l_i = _partial_attention(q, k_c, v_c, b_c, scale)
        return _merge_partial(acc, m, l, pv_i, m_i, l_i), None

    (acc, m, l), _ = jax.lax.scan(step, (acc0, m0, l0), xs)
    return (acc / l[..., None]).astype(q.dtype)


@functools.lru_cache(maxsize=64)
def _sharded_ring_fn(
    mesh: Mesh,
    axis_name: str,
    dropout_rate: float,
    deterministic: bool,
    has_bias: bool,
    has_rng: bool,
):
    """Build + jit the sharded ring program once per static configuration.

    The eager call path matters: an unjitted ``shard_map`` dispatches
    op-by-op across the virtual devices (measured ~10x slower than the
    compile itself on an 8-device CPU mesh), so the wrapper jits and the
    cache keys on everything static. The dropout key is a traced argument
    (replicated spec), so re-keying dropout reuses the same executable."""
    seq_spec = P(None, None, axis_name, None)
    bias_spec = P(None, None, None, axis_name)

    def call(q, k, v, *rest):
        bias = rest[0] if has_bias else None
        rng = rest[-1] if has_rng else None
        args = (q, k, v) if bias is None else (q, k, v, bias)
        return ring_attention(
            *args,
            axis_name=axis_name,
            dropout_rate=dropout_rate,
            dropout_rng=rng,
            deterministic=deterministic,
        )

    in_specs = (
        (seq_spec,) * 3
        + ((bias_spec,) if has_bias else ())
        + ((P(),) if has_rng else ())
    )
    from .mesh import shard_map

    return jax.jit(
        shard_map(
            call, mesh=mesh, in_specs=in_specs, out_specs=seq_spec
        )
    )


def ring_attention_sharded(
    q: jnp.ndarray,  # [B, H, L, D] — full arrays
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: jnp.ndarray | None = None,
    *,
    mesh: Mesh,
    axis_name: str = "seq",
    dropout_rate: float = 0.0,
    dropout_rng: jax.Array | None = None,
    deterministic: bool = True,
) -> jnp.ndarray:
    """Standalone wrapper: shards the sequence axis of full [B, H, L, D]
    arrays over ``axis_name`` and runs the ring. The model-integrated path
    instead runs the whole encoder under one ``shard_map``."""
    fn = _sharded_ring_fn(
        mesh,
        axis_name,
        float(dropout_rate),
        bool(deterministic),
        bias is not None,
        dropout_rng is not None,
    )
    args = (q, k, v) + ((bias,) if bias is not None else ())
    if dropout_rng is not None:
        args += (dropout_rng,)
    return fn(*args)
