"""FedAvg as an XLA collective.

The reference's aggregation pipeline is: each client gzip-pickles a 245 MB
state dict, ships it over TCP (client1.py:276-295), a server thread decodes it
(server.py:57-65), a Python loop computes an in-place unweighted mean
(server.py:67-79, 0.36 s host-side), and a second socket broadcasts the result
back (server.py:81-114). Total round path: minutes of serialize/transfer.

Here the whole pipeline is one jitted mean over the stacked client axis of a
``[C, ...]``-parameter pytree sharded over the ``clients`` mesh axis — XLA
lowers it to an all-reduce on ICI and the broadcast is implicit (the output is
the already-replicated mean written back to every client's shard). Weights
never leave the devices; there is no serialization step at all.

Capabilities beyond the reference:
* weighted FedAvg (weight clients by sample count),
* masked FedAvg (dropped/failed clients excluded from the mean — the
  reference instead hangs its accept loop, server.py:69-71,124-132).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp

from .mesh import FedShardings


def stack_params(params: Any, num_clients: int) -> Any:
    """Single-model params -> the ``[C, ...]`` stacked layout (every row
    identical — the reference's shared pretrained start, client1.py:56).
    The one definition of the per-client leading axis, shared by the
    federated trainer, the fedseq composition, and tests."""
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (num_clients, *x.shape)), params
    )


def weighted_mean(
    stacked_params: Any,
    weights: jnp.ndarray | None = None,
    mask: jnp.ndarray | None = None,
) -> Any:
    """Weighted, masked mean over the leading (clients) axis — the
    single-model fp32 result, NOT broadcast back (fedavg adds that).

    ``weights``: [C] client weights (e.g. local sample counts); uniform if
    None — the reference's unweighted mean (server.py:73-76).
    ``mask``: [C] 0/1 survivors; masked-out clients contribute nothing and
    the divisor shrinks accordingly.
    """
    leaves = jax.tree.leaves(stacked_params)
    if not leaves:
        return stacked_params
    C = leaves[0].shape[0]
    w = jnp.ones((C,), jnp.float32) if weights is None else weights.astype(jnp.float32)
    if mask is not None:
        w = w * mask.astype(jnp.float32)
    denom = jnp.maximum(w.sum(), 1e-9)
    wn = w / denom

    def _avg(x: jnp.ndarray) -> jnp.ndarray:
        wshape = (C,) + (1,) * (x.ndim - 1)
        # fp32 accumulation regardless of param dtype
        return (x.astype(jnp.float32) * wn.reshape(wshape)).sum(axis=0)

    return jax.tree.map(_avg, stacked_params)


def fedavg(
    stacked_params: Any,
    weights: jnp.ndarray | None = None,
    mask: jnp.ndarray | None = None,
) -> Any:
    """:func:`weighted_mean` broadcast back to ``[C, ...]`` so each client
    shard receives the average."""
    mean = weighted_mean(stacked_params, weights, mask)
    return jax.tree.map(
        lambda m, x: jnp.broadcast_to(m.astype(x.dtype), x.shape),
        mean,
        stacked_params,
    )


def make_server_optimizer(fed_cfg) -> "optax.GradientTransformation | None":
    """The FedOpt server optimizer (Reddi et al.): applied to the round's
    mean update at the aggregation boundary. "momentum" = FedAvgM (SGD with
    heavy-ball momentum over round updates), "adam" = FedAdam, "yogi" =
    FedYogi (additive second moment — more stable under the bursty
    pseudo-gradient variance of non-IID rounds). At server_lr=1 with no
    momentum, the step reduces exactly to plain FedAvg (new global = mean).

    Shared by the SPMD mesh tier (FederatedTrainer) and the TCP tier's
    strategy registry (strategies/core.py), which wraps it around the
    streamed fold's finalize-time mean. The transform's optimizer state
    is checkpointable across server restarts via the strategy layer's
    export_state/restore_state (``serve --strategy-state-file``): optax
    states here are flat pytrees of arrays whose structure is a pure
    function of the (sorted-key) fp32 param template, which is what lets
    a restarted server rebuild the treedef and re-adopt the leaves."""
    import optax

    if fed_cfg.server_opt == "momentum":
        return optax.sgd(fed_cfg.server_lr, momentum=fed_cfg.server_momentum)
    if fed_cfg.server_opt == "adam":
        return optax.adam(fed_cfg.server_lr)
    if fed_cfg.server_opt == "yogi":
        return optax.yogi(fed_cfg.server_lr)
    return None


def make_fedavg_step(shardings: FedShardings) -> Callable:
    """Jitted FedAvg over the mesh: inputs/outputs sharded ``P('clients')``,
    so the mean lowers to a cross-client all-reduce on ICI."""

    @partial(
        jax.jit,
        in_shardings=(shardings.client, None, None),
        out_shardings=shardings.client,
        static_argnums=(),
    )
    def step(stacked_params, weights, mask):
        return fedavg(stacked_params, weights, mask)

    return step
