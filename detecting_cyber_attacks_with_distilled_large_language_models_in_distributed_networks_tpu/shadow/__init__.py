"""Shadow evaluation plane: live-traffic A/B before the pointer moves.

The registry ladder's ``shadow`` state finally carries traffic: the
router duplicates a deterministic sample of live scoring requests onto
the candidate artifact (:mod:`.mirror` — fire-and-forget on a bounded
queue, bench-asserted zero added serving p99), the serving/shadow
probability pairs accumulate into flip-rate + PSI disagreement evidence
(:mod:`.compare` — atomic paired JSONL + status file), and promotion is
gated on that LIVE evidence (:mod:`.gate` — under-threshold
disagreement promotes, anything else fails closed to ``rejected`` with
the verdict on the registry event). ``fedtpu controller --shadow-gate``
drives the gate; ``fedtpu fleet --shadow-sample N`` arms the mirror;
``fedtpu shadow status|report`` is the operator surface.
"""

from .compare import PAIR_SCHEMA, ShadowCompare, evaluate_status
from .gate import (
    ShadowGate,
    pairs_path,
    read_status,
    shadow_dir,
    status_path,
)
from .mirror import ShadowMirror

__all__ = [
    "PAIR_SCHEMA",
    "ShadowCompare",
    "ShadowGate",
    "ShadowMirror",
    "evaluate_status",
    "pairs_path",
    "read_status",
    "shadow_dir",
    "status_path",
]
