"""Disagreement-gated promotion: the candidate earns serving on live
traffic, or it doesn't serve.

The controller's eval gate answers "is the candidate at least as good on
the held-out split?" — a necessary check that says nothing about the
traffic actually hitting the fleet. :class:`ShadowGate` adds the second,
live question: with the candidate held in the registry ``shadow`` state
and the fleet manager mirroring sampled traffic onto it (shadow/mirror +
shadow/compare), the gate waits for at least ``min_pairs`` mirrored
pairs and promotes only when the measured disagreement (flip rate AND
paired-score PSI) sits under threshold. Everything else **fails closed**
to ``rejected`` — a regression, an uncomputable distance, or a timeout
with too little evidence all leave the serving pointer exactly where it
was, with the verdict recorded on the registry event.

Coordination is file-shaped, like the rest of the control plane: the
comparator (running inside the fleet-manager process) atomically
publishes ``<registry>/shadow/<artifact>.status.json``; the gate
(running inside the controller process) polls it. Clock and sleep are
injectable so the gate's whole decision surface unit-tests without a
wall clock.
"""

from __future__ import annotations

import json
import os
import time

from ..utils.logging import get_logger
from .compare import evaluate_status

log = get_logger()


def shadow_dir(registry_root: str) -> str:
    """Where the shadow plane's per-artifact evidence lands (under the
    registry root — the control plane's one coordination directory)."""
    return os.path.join(os.path.abspath(registry_root), "shadow")


def status_path(registry_root: str, aid: str) -> str:
    return os.path.join(shadow_dir(registry_root), f"{aid}.status.json")


def pairs_path(registry_root: str, aid: str) -> str:
    return os.path.join(shadow_dir(registry_root), f"{aid}.pairs.jsonl")


def read_status(registry_root: str, aid: str) -> dict | None:
    """The comparator's latest atomic snapshot for ``aid`` (None before
    the first publish; a torn/corrupt file reads as absent — the writer
    uses tmp+replace, so this is a foreign-writer guard, not a race)."""
    try:
        with open(status_path(registry_root, aid)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


class ShadowGate:
    """Block until the shadow plane produced a verdict for an artifact.

    ``wait(aid)`` returns ``(ok, verdict)``; the caller (the controller)
    promotes on ok and rejects otherwise, attaching ``verdict`` to the
    registry event either way. ``clock``/``sleep`` are injectable — the
    timeout path is pure (now, status) arithmetic."""

    def __init__(
        self,
        registry_root: str,
        *,
        min_pairs: int = 256,
        max_flip_rate: float = 0.02,
        psi_threshold: float = 0.25,
        timeout_s: float = 600.0,
        poll_s: float = 0.5,
        tracer=None,
        clock=time.monotonic,
        sleep=time.sleep,
    ):
        if int(min_pairs) < 1:
            raise ValueError(f"min_pairs={min_pairs} must be >= 1")
        if not 0.0 <= float(max_flip_rate) <= 1.0:
            raise ValueError(
                f"max_flip_rate={max_flip_rate} must be in [0, 1]"
            )
        if float(psi_threshold) <= 0.0:
            raise ValueError(
                f"psi_threshold={psi_threshold} must be > 0"
            )
        if float(timeout_s) <= 0.0:
            raise ValueError(f"timeout_s={timeout_s} must be > 0")
        self.registry_root = os.path.abspath(registry_root)
        self.min_pairs = int(min_pairs)
        self.max_flip_rate = float(max_flip_rate)
        self.psi_threshold = float(psi_threshold)
        self.timeout_s = float(timeout_s)
        self.poll_s = float(poll_s)
        self.tracer = tracer
        self._clock = clock
        self._sleep = sleep

    def _verdict(self, ok: bool, reason: str, status: dict | None) -> dict:
        status = status or {}
        return {
            "ok": bool(ok),
            "reason": reason,
            "pairs": int(status.get("pairs", 0) or 0),
            "flip_rate": status.get("flip_rate"),
            "mean_abs_dprob": status.get("mean_abs_dprob"),
            "psi": status.get("psi"),
            "min_pairs": self.min_pairs,
            "max_flip_rate": self.max_flip_rate,
            "psi_threshold": self.psi_threshold,
        }

    def wait(self, aid: str) -> tuple[bool, dict]:
        """Poll the comparator's status until >= ``min_pairs`` pairs
        accumulated (then rule on the evidence) or the timeout expires
        (then FAIL CLOSED — a candidate that never earned its evidence
        never earns the pointer)."""
        t_unix = time.time()
        t0 = self._clock()
        deadline = t0 + self.timeout_s
        status: dict | None = None
        while True:
            status = read_status(self.registry_root, aid)
            if status is not None and int(status.get("pairs", 0) or 0) >= (
                self.min_pairs
            ):
                ok, reason = evaluate_status(
                    status,
                    min_pairs=self.min_pairs,
                    max_flip_rate=self.max_flip_rate,
                    psi_threshold=self.psi_threshold,
                )
                verdict = self._verdict(ok, reason, status)
                break
            if self._clock() >= deadline:
                pairs = int((status or {}).get("pairs", 0) or 0)
                verdict = self._verdict(
                    False,
                    f"shadow gate timeout after {self.timeout_s:.0f}s "
                    f"with {pairs} mirrored pair(s) < "
                    f"min_pairs={self.min_pairs} (no live evidence — "
                    "failing closed)",
                    status,
                )
                ok = False
                break
            self._sleep(self.poll_s)
        if self.tracer is not None:
            self.tracer.record(
                "shadow-gate",
                t_start=t_unix,
                dur_s=self._clock() - t0,
                artifact=aid,
                passed=bool(ok),
                pairs=verdict["pairs"],
                flip_rate=verdict["flip_rate"],
                psi=verdict["psi"],
            )
        log.info(
            f"[SHADOW] gate verdict for {aid}: "
            f"{'PASS' if ok else 'FAIL'} ({verdict['reason']})"
        )
        return ok, verdict
