"""Live-traffic mirroring onto the shadow artifact — never in the way.

The registry ladder has had a ``shadow`` state since the control plane
landed, but no traffic ever flowed through it: promotion gated on
held-out offline eval alone, which is exactly the gate that misses
live-distribution drift (arXiv:2509.17836 — federated cybersecurity
deployments degrade under non-IID, shifting traffic that the validation
split never saw). :class:`ShadowMirror` closes the traffic half of that
gap: hooked into the router's forward path (router/core.py
``set_mirror``), it duplicates a deterministic counter-strided sample of
live scoring requests onto a shadow backend, so the candidate scores the
SAME flows the incumbent scores, at the same moment, on real traffic.

The one non-negotiable invariant is that the serving path must not be
able to tell the mirror exists:

* ``admit()`` — the only call on the serving hot path — is a counter
  increment plus a bounded-queue ``put_nowait``: no RNG (the same
  no-wall-clock/no-entropy discipline as serve-batch trace sampling —
  reruns mirror the same requests), no I/O, no blocking. A **full queue
  drops the mirror copy** (counted, never retried) — backpressure from a
  slow shadow replica sheds shadow work, never delays a live reply.
* The actual duplicate send, the shadow connection, and the reply
  decode all live on the mirror's own worker/reader threads. A **dead
  shadow replica degrades to pass-through**: dials fail quietly on a
  monotonic backoff, every affected pair is abandoned, and the serving
  tier's p99 is bench-asserted unchanged (``shadow_added_p99_ms``).

The mirror is model-free like the router: it re-addresses the already-
encoded request frame (serving/protocol.py ``rewrite_id``) to its pair
key and ships the bytes — no tokenize, no JSON rebuild. Replies come
back id-matched on the single shadow connection and land in the
comparator (shadow/compare.py) as the pair's shadow side.
"""

from __future__ import annotations

import queue
import socket
import threading
import time

from ..comm import framing
from ..comm.wire import WireError
from ..obs import metrics as obs_metrics
from ..serving import protocol
from ..serving.client import _set_nodelay, answer_auth_challenge
from ..serving.server import MAX_REQUEST_FRAME
from ..utils.logging import get_logger

log = get_logger()


class ShadowMirror:
    """Fire-and-forget duplicator of sampled scoring requests.

    Router contract (router/core.py): ``admit(frame)`` on the forward
    path returns a mirror id when this request was sampled and enqueued
    (None otherwise — not sampled, or the queue was full and the COPY
    was dropped); ``note_serving_reply(mid, frame)`` hands the serving
    side of a sampled pair to the comparator; ``abandon(mid)`` sheds a
    pair whose serving half died (eject, no replica).

    ``sample`` is the stride: mirror one request in ``sample`` via the
    admission counter — deterministic, no RNG. 1 mirrors everything.
    """

    def __init__(
        self,
        host: str,
        port: int,
        *,
        sample: int = 1,
        compare=None,
        auth_key: bytes | None = None,
        max_queue: int = 256,
        connect_timeout_s: float = 5.0,
        redial_interval_s: float = 1.0,
        tracer=None,
        span_stride: int = 64,
    ):
        if int(sample) < 1:
            raise ValueError(f"sample={sample} must be >= 1 (the stride)")
        self.host = host
        self.port = int(port)
        self.sample = int(sample)
        self.compare = compare
        self.auth_key = auth_key
        self.connect_timeout_s = float(connect_timeout_s)
        self.redial_interval_s = float(redial_interval_s)
        self.tracer = tracer
        self._span_stride = max(1, int(span_stride))
        self._lock = threading.Lock()
        self._seen = 0
        self._next_mid = 0
        self._mirrored = 0
        self._dropped = 0
        self._errors = 0
        self._inflight: set[int] = set()
        self._q: "queue.Queue[tuple[int, bytes] | None]" = queue.Queue(
            maxsize=max(1, int(max_queue))
        )
        # Serving-side pair completions ride their own bounded queue to
        # a mirror-owned thread: completing a pair appends the paired
        # JSONL record and rewrites status.json, and that disk I/O must
        # not run on the ROUTER's backend reply thread (it would delay
        # every multiplexed live reply behind it — the exact invariant
        # the mirror exists to keep). Full queue = the pair is shed.
        self._cq: "queue.Queue[tuple[str, int, bytes | None] | None]" = (
            queue.Queue(maxsize=max(4 * int(max_queue), 1024))
        )
        self._sock: socket.socket | None = None
        self._next_dial = 0.0
        self._closed = threading.Event()
        self._threads: list[threading.Thread] = []
        m = obs_metrics.default_registry()
        self._m_mirrored = m.counter(
            "fedtpu_shadow_mirrored_total",
            help="live scoring requests duplicated onto the shadow backend",
        )
        self._m_dropped = m.counter(
            "fedtpu_shadow_mirror_dropped_total",
            help="mirror copies dropped (bounded queue full) — the live "
            "request was never delayed",
        )
        self._m_errors = m.counter(
            "fedtpu_shadow_errors_total",
            help="mirror sends/replies lost to a dead or failing shadow "
            "backend (pass-through: serving unaffected)",
        )

    # --------------------------------------------------------------- control
    def start(self) -> "ShadowMirror":
        for target, name in (
            (self._worker, "mirror"),
            (self._compare_loop, "compare"),
        ):
            t = threading.Thread(
                target=target, name=f"fedtpu-shadow-{name}", daemon=True
            )
            t.start()
            self._threads.append(t)
        log.info(
            f"[SHADOW] mirroring 1/{self.sample} of live requests onto "
            f"{self.host}:{self.port} (queue {self._q.maxsize})"
        )
        return self

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        for q in (self._q, self._cq):
            try:
                q.put_nowait(None)  # wake the workers
            except queue.Full:
                pass
        self._teardown_conn()
        for t in self._threads:
            t.join(timeout=5.0)
        s = self.stats()
        log.info(
            f"[SHADOW] mirror closed: {s['mirrored']} mirrored, "
            f"{s['dropped']} dropped (queue full), {s['errors']} "
            "shadow-side errors"
        )

    def __enter__(self) -> "ShadowMirror":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def stats(self) -> dict:
        with self._lock:
            return {
                "seen": self._seen,
                "mirrored": self._mirrored,
                "dropped": self._dropped,
                "errors": self._errors,
                "inflight": len(self._inflight),
                "sample": self.sample,
            }

    # ------------------------------------------------------- serving-path API
    def admit(self, frame: bytes) -> int | None:
        """Counter-strided sampling decision + O(1) enqueue. Runs ON the
        router's client loop: a counter increment, a dict-free stride
        check, and one ``put_nowait`` — never blocks, never raises out.
        Returns the pair key (mirror id) or None."""
        with self._lock:
            self._seen += 1
            if (self._seen - 1) % self.sample != 0:
                return None
            self._next_mid += 1
            mid = self._next_mid
        try:
            self._q.put_nowait((mid, bytes(frame)))
        except queue.Full:
            # The mirror copy is SHED — the live request proceeds
            # untouched, and no pair is ever opened for this id.
            with self._lock:
                self._dropped += 1
            self._m_dropped.inc()
            return None
        with self._lock:
            self._mirrored += 1
            mirrored = self._mirrored
        self._m_mirrored.inc()
        if self.tracer is not None and (
            (mirrored - 1) % self._span_stride == 0
        ):
            self.tracer.record(
                "shadow-mirror",
                t_start=time.time(),
                dur_s=0.0,
                mirrored=mirrored,
                sampled_requests=(
                    self._span_stride if self._span_stride > 1 else None
                ),
            )
        return mid

    def note_serving_reply(self, mid: int, frame: bytes) -> None:
        """The serving side of a sampled pair arrived (router reply
        path). ONE bounded put_nowait and nothing else runs here: the
        parse, the pairing, and the pair-completion disk I/O all happen
        on the mirror's compare thread — the router's reply path must
        never wait on the comparator's JSONL/status writes. A full
        queue sheds the pair (counted)."""
        if self.compare is None:
            return
        try:
            self._cq.put_nowait(("serving", mid, bytes(frame)))
        except queue.Full:
            self._count_error(None)

    def abandon(self, mid: int) -> None:
        """Shed a pair (router path: eject / no replica / send failed).
        Same one-enqueue discipline as :meth:`note_serving_reply`; on a
        full queue the half-open entry is left to the comparator's
        bounded-pending eviction."""
        if self.compare is None:
            return
        try:
            self._cq.put_nowait(("abandon", mid, None))
        except queue.Full:
            self._count_error(None)

    def _compare_loop(self) -> None:
        """Drain serving-side completions into the comparator. A reject
        (shed request) abandons the pair — there is no serving
        probability to compare."""
        while True:
            try:
                item = self._cq.get(timeout=0.2)
            except queue.Empty:
                if self._closed.is_set():
                    return
                continue
            if item is None or self._closed.is_set():
                return
            kind, mid, payload = item
            if kind == "abandon":
                self.compare.abandon(mid)
                continue
            try:
                if protocol.is_reject(payload):
                    self.compare.abandon(mid)
                    continue
                prob = float(protocol.parse_reply(payload)["prob"])
            except (WireError, TypeError, ValueError):
                self.compare.abandon(mid)
                continue
            self.compare.note_serving(mid, prob)

    # ------------------------------------------------------- shadow-side work
    def _count_error(self, mid: int | None = None) -> None:
        with self._lock:
            self._errors += 1
        self._m_errors.inc()
        if mid is not None:
            self.abandon(mid)

    def _teardown_conn(self) -> None:
        with self._lock:
            sock, self._sock = self._sock, None
            stranded = list(self._inflight)
            self._inflight.clear()
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        for mid in stranded:
            self.abandon(mid)

    def _ensure_conn(self) -> socket.socket | None:
        """Dial the shadow backend lazily, at most once per
        ``redial_interval_s`` — a DEAD shadow replica must cost the
        worker one bounded connect attempt per interval, not one per
        mirrored request (pass-through, cheaply)."""
        with self._lock:
            if self._sock is not None:
                return self._sock
        now = time.monotonic()
        if now < self._next_dial:
            return None
        self._next_dial = now + self.redial_interval_s
        try:
            sock = socket.create_connection(
                (self.host, self.port), timeout=self.connect_timeout_s
            )
            sock.settimeout(None)
            _set_nodelay(sock)
            if self.auth_key is not None:
                sock.settimeout(self.connect_timeout_s)
                answer_auth_challenge(sock, self.auth_key)
                sock.settimeout(None)
        except (OSError, ConnectionError, WireError) as e:
            log.debug(f"[SHADOW] shadow backend dial failed: {e}")
            return None
        with self._lock:
            self._sock = sock
        threading.Thread(
            target=self._reader, args=(sock,), daemon=True
        ).start()
        return sock

    def _worker(self) -> None:
        """Drain the bounded queue onto the shadow connection. Only this
        thread ever writes the socket, so frames cannot interleave."""
        while True:
            try:
                item = self._q.get(timeout=0.2)
            except queue.Empty:
                if self._closed.is_set():
                    return
                continue
            if item is None or self._closed.is_set():
                return
            mid, frame = item
            sock = self._ensure_conn()
            if sock is None:
                self._count_error(mid)
                continue
            try:
                out = protocol.rewrite_id(frame, mid)
            except WireError:
                self._count_error(mid)
                continue
            with self._lock:
                self._inflight.add(mid)
            try:
                framing.send_frame(sock, out, await_ack=False)
            except (OSError, ConnectionError):
                self._count_error(None)
                with self._lock:
                    self._inflight.discard(mid)
                self.abandon(mid)
                self._teardown_conn()

    def _reader(self, sock: socket.socket) -> None:
        """Resolve shadow replies by the protocol's id echo — the pair's
        shadow side goes to the comparator; rejects abandon the pair."""
        while not self._closed.is_set():
            try:
                frame = bytes(
                    framing.recv_frame(
                        sock, send_ack=False, max_frame=MAX_REQUEST_FRAME
                    )
                )
                mid = protocol.frame_id(frame)
            except (OSError, ConnectionError, WireError):
                with self._lock:
                    lost = self._sock is sock
                if lost:
                    self._count_error(None)
                    self._teardown_conn()
                return
            with self._lock:
                known = mid in self._inflight
                self._inflight.discard(mid)
            if not known or self.compare is None:
                continue
            try:
                if protocol.is_reject(frame):
                    self.compare.abandon(mid)
                else:
                    self.compare.note_shadow(
                        mid, float(protocol.parse_reply(frame)["prob"])
                    )
            except (WireError, TypeError, ValueError):
                self._count_error(mid)
