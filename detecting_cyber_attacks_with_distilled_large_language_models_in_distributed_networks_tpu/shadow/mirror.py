"""Live-traffic mirroring onto the shadow artifact — never in the way.

The registry ladder has had a ``shadow`` state since the control plane
landed, but no traffic ever flowed through it: promotion gated on
held-out offline eval alone, which is exactly the gate that misses
live-distribution drift (arXiv:2509.17836 — federated cybersecurity
deployments degrade under non-IID, shifting traffic that the validation
split never saw). :class:`ShadowMirror` closes the traffic half of that
gap: hooked into the router's forward path (router/core.py
``set_mirror``), it duplicates a deterministic counter-strided sample of
live scoring requests onto a shadow backend, so the candidate scores the
SAME flows the incumbent scores, at the same moment, on real traffic.

The one non-negotiable invariant is that the serving path must not be
able to tell the mirror exists:

* ``admit()`` — the only call on the serving hot path — is a counter
  increment plus a bounded-queue ``put_nowait``: no RNG (the same
  no-wall-clock/no-entropy discipline as serve-batch trace sampling —
  reruns mirror the same requests), no I/O, no blocking. A **full queue
  drops the mirror copy** (counted, never retried) — backpressure from a
  slow shadow replica sheds shadow work, never delays a live reply.
* The actual duplicate send, the shadow connection, and the reply
  decode all live on the mirror's own worker/reader threads. A **dead
  shadow replica degrades to pass-through**: dials fail quietly on a
  monotonic backoff, every affected pair is abandoned, and the serving
  tier's p99 is bench-asserted unchanged (``shadow_added_p99_ms``).

The mirror is model-free like the router: it re-addresses the already-
encoded request frame (serving/protocol.py ``rewrite_id``) to its pair
key and ships the bytes — no tokenize, no JSON rebuild. Replies come
back id-matched on the single shadow connection and land in the
comparator (shadow/compare.py) as the pair's shadow side.
"""

from __future__ import annotations

import queue
import socket
import threading
import time

from ..comm import framing
from ..comm.wire import WireError
from ..obs import metrics as obs_metrics
from ..serving import protocol
from ..serving.client import _set_nodelay, answer_auth_challenge
from ..serving.server import MAX_REQUEST_FRAME
from ..utils.logging import get_logger

log = get_logger()


class ShadowMirror:
    """Fire-and-forget duplicator of sampled scoring requests.

    Router contract (router/core.py): ``admit(frame)`` on the forward
    path returns a mirror id when this request was sampled and enqueued
    (None otherwise — not sampled, or the queue was full and the COPY
    was dropped); ``note_serving_reply(mid, frame)`` hands the serving
    side of a sampled pair to the comparator; ``abandon(mid)`` sheds a
    pair whose serving half died (eject, no replica).

    ``sample`` is the stride: mirror one request in ``sample`` via the
    admission counter — deterministic, no RNG. 1 mirrors everything.

    ``extra_targets`` (ISSUE 18) appends ranked secondary candidates:
    mirrored requests stride across the target list by mirror id
    (``(mid - 1) % n_targets`` — deterministic like the admission
    stride), each target gets its own connection + redial backoff, and
    replies land in the comparator tagged with the candidate's RANK so
    the aggregate gate evidence stays rank-0-only.
    """

    def __init__(
        self,
        host: str,
        port: int,
        *,
        sample: int = 1,
        compare=None,
        auth_key: bytes | None = None,
        max_queue: int = 256,
        connect_timeout_s: float = 5.0,
        redial_interval_s: float = 1.0,
        tracer=None,
        span_stride: int = 64,
        extra_targets: tuple = (),
    ):
        if int(sample) < 1:
            raise ValueError(f"sample={sample} must be >= 1 (the stride)")
        self.host = host
        self.port = int(port)
        self.targets: tuple[tuple[str, int], ...] = (
            (host, int(port)),
        ) + tuple((h, int(p)) for h, p in extra_targets)
        self.sample = int(sample)
        self.compare = compare
        self.auth_key = auth_key
        self.connect_timeout_s = float(connect_timeout_s)
        self.redial_interval_s = float(redial_interval_s)
        self.tracer = tracer
        self._span_stride = max(1, int(span_stride))
        self._lock = threading.Lock()
        self._seen = 0
        self._next_mid = 0
        self._mirrored = 0
        self._dropped = 0
        self._errors = 0
        n_targets = len(self.targets)
        self._inflight: list[set[int]] = [set() for _ in range(n_targets)]
        self._socks: list[socket.socket | None] = [None] * n_targets
        self._next_dials: list[float] = [0.0] * n_targets
        self._q: "queue.Queue[tuple[int, bytes] | None]" = queue.Queue(
            maxsize=max(1, int(max_queue))
        )
        # Serving-side pair completions ride their own bounded queue to
        # a mirror-owned thread: completing a pair appends the paired
        # JSONL record and rewrites status.json, and that disk I/O must
        # not run on the ROUTER's backend reply thread (it would delay
        # every multiplexed live reply behind it — the exact invariant
        # the mirror exists to keep). Full queue = the pair is shed.
        self._cq: "queue.Queue[tuple[str, int, bytes | None] | None]" = (
            queue.Queue(maxsize=max(4 * int(max_queue), 1024))
        )
        self._closed = threading.Event()
        self._threads: list[threading.Thread] = []
        m = obs_metrics.default_registry()
        self._m_mirrored = m.counter(
            "fedtpu_shadow_mirrored_total",
            help="live scoring requests duplicated onto the shadow backend",
        )
        self._m_dropped = m.counter(
            "fedtpu_shadow_mirror_dropped_total",
            help="mirror copies dropped (bounded queue full) — the live "
            "request was never delayed",
        )
        self._m_errors = m.counter(
            "fedtpu_shadow_errors_total",
            help="mirror sends/replies lost to a dead or failing shadow "
            "backend (pass-through: serving unaffected)",
        )

    # --------------------------------------------------------------- control
    def start(self) -> "ShadowMirror":
        for target, name in (
            (self._worker, "mirror"),
            (self._compare_loop, "compare"),
        ):
            t = threading.Thread(
                target=target, name=f"fedtpu-shadow-{name}", daemon=True
            )
            t.start()
            self._threads.append(t)
        log.info(
            f"[SHADOW] mirroring 1/{self.sample} of live requests onto "
            f"{self.host}:{self.port} (queue {self._q.maxsize})"
        )
        return self

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        for q in (self._q, self._cq):
            try:
                q.put_nowait(None)  # wake the workers
            except queue.Full:
                pass
        self._teardown_conn()
        for t in self._threads:
            t.join(timeout=5.0)
        s = self.stats()
        log.info(
            f"[SHADOW] mirror closed: {s['mirrored']} mirrored, "
            f"{s['dropped']} dropped (queue full), {s['errors']} "
            "shadow-side errors"
        )

    def __enter__(self) -> "ShadowMirror":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def stats(self) -> dict:
        with self._lock:
            return {
                "seen": self._seen,
                "mirrored": self._mirrored,
                "dropped": self._dropped,
                "errors": self._errors,
                "inflight": sum(len(s) for s in self._inflight),
                "sample": self.sample,
                "targets": len(self.targets),
            }

    # ------------------------------------------------------- serving-path API
    def admit(self, frame: bytes) -> int | None:
        """Counter-strided sampling decision + O(1) enqueue. Runs ON the
        router's client loop: a counter increment, a dict-free stride
        check, and one ``put_nowait`` — never blocks, never raises out.
        Returns the pair key (mirror id) or None."""
        with self._lock:
            self._seen += 1
            if (self._seen - 1) % self.sample != 0:
                return None
            self._next_mid += 1
            mid = self._next_mid
        # Thread the live request's id to the comparator BEFORE the
        # rewrite erases it — the ground-truth plane joins on it. One
        # header parse for sampled requests only; failures are ignored
        # (the pair still works, it just can't be label-joined).
        reg = getattr(self.compare, "register_rid", None)
        if reg is not None:
            try:
                reg(mid, str(protocol.frame_id(frame)))
            except (WireError, TypeError, ValueError):
                pass
        try:
            self._q.put_nowait((mid, bytes(frame)))
        except queue.Full:
            # The mirror copy is SHED — the live request proceeds
            # untouched, and no pair is ever opened for this id.
            with self._lock:
                self._dropped += 1
            self._m_dropped.inc()
            return None
        with self._lock:
            self._mirrored += 1
            mirrored = self._mirrored
        self._m_mirrored.inc()
        if self.tracer is not None and (
            (mirrored - 1) % self._span_stride == 0
        ):
            self.tracer.record(
                "shadow-mirror",
                t_start=time.time(),
                dur_s=0.0,
                mirrored=mirrored,
                sampled_requests=(
                    self._span_stride if self._span_stride > 1 else None
                ),
            )
        return mid

    def note_serving_reply(self, mid: int, frame: bytes) -> None:
        """The serving side of a sampled pair arrived (router reply
        path). ONE bounded put_nowait and nothing else runs here: the
        parse, the pairing, and the pair-completion disk I/O all happen
        on the mirror's compare thread — the router's reply path must
        never wait on the comparator's JSONL/status writes. A full
        queue sheds the pair (counted)."""
        if self.compare is None:
            return
        try:
            self._cq.put_nowait(("serving", mid, bytes(frame)))
        except queue.Full:
            self._count_error(None)

    def abandon(self, mid: int) -> None:
        """Shed a pair (router path: eject / no replica / send failed).
        Same one-enqueue discipline as :meth:`note_serving_reply`; on a
        full queue the half-open entry is left to the comparator's
        bounded-pending eviction."""
        if self.compare is None:
            return
        try:
            self._cq.put_nowait(("abandon", mid, None))
        except queue.Full:
            self._count_error(None)

    def _compare_loop(self) -> None:
        """Drain serving-side completions into the comparator. A reject
        (shed request) abandons the pair — there is no serving
        probability to compare."""
        while True:
            try:
                item = self._cq.get(timeout=0.2)
            except queue.Empty:
                if self._closed.is_set():
                    return
                continue
            if item is None or self._closed.is_set():
                return
            kind, mid, payload = item
            if kind == "abandon":
                self.compare.abandon(mid)
                continue
            try:
                if protocol.is_reject(payload):
                    self.compare.abandon(mid)
                    continue
                prob = float(protocol.parse_reply(payload)["prob"])
            except (WireError, TypeError, ValueError):
                self.compare.abandon(mid)
                continue
            self.compare.note_serving(mid, prob)

    # ------------------------------------------------------- shadow-side work
    def _count_error(self, mid: int | None = None) -> None:
        with self._lock:
            self._errors += 1
        self._m_errors.inc()
        if mid is not None:
            self.abandon(mid)

    def _teardown_conn(self, idx: int | None = None) -> None:
        """Tear down one target's connection (all of them on close)."""
        indices = range(len(self.targets)) if idx is None else (idx,)
        for i in indices:
            with self._lock:
                sock, self._socks[i] = self._socks[i], None
                stranded = list(self._inflight[i])
                self._inflight[i].clear()
            if sock is not None:
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    sock.close()
                except OSError:
                    pass
            for mid in stranded:
                self.abandon(mid)

    def _ensure_conn(self, idx: int) -> socket.socket | None:
        """Dial one shadow target lazily, at most once per
        ``redial_interval_s`` — a DEAD shadow replica must cost the
        worker one bounded connect attempt per interval, not one per
        mirrored request (pass-through, cheaply). Each target backs off
        independently: one dead secondary never throttles the rest."""
        with self._lock:
            if self._socks[idx] is not None:
                return self._socks[idx]
        now = time.monotonic()
        if now < self._next_dials[idx]:
            return None
        self._next_dials[idx] = now + self.redial_interval_s
        host, port = self.targets[idx]
        try:
            sock = socket.create_connection(
                (host, port), timeout=self.connect_timeout_s
            )
            sock.settimeout(None)
            _set_nodelay(sock)
            if self.auth_key is not None:
                sock.settimeout(self.connect_timeout_s)
                answer_auth_challenge(sock, self.auth_key)
                sock.settimeout(None)
        except (OSError, ConnectionError, WireError) as e:
            log.debug(f"[SHADOW] shadow backend {host}:{port} dial failed: {e}")
            return None
        with self._lock:
            self._socks[idx] = sock
        threading.Thread(
            target=self._reader, args=(sock, idx), daemon=True
        ).start()
        return sock

    def _worker(self) -> None:
        """Drain the bounded queue onto the shadow connections. Only this
        thread ever writes a socket, so frames cannot interleave. With a
        ranked target list, the mirror id picks the target — the same
        deterministic stride discipline as admission sampling."""
        n_targets = len(self.targets)
        while True:
            try:
                item = self._q.get(timeout=0.2)
            except queue.Empty:
                if self._closed.is_set():
                    return
                continue
            if item is None or self._closed.is_set():
                return
            mid, frame = item
            idx = (mid - 1) % n_targets
            sock = self._ensure_conn(idx)
            if sock is None:
                self._count_error(mid)
                continue
            try:
                out = protocol.rewrite_id(frame, mid)
            except WireError:
                self._count_error(mid)
                continue
            with self._lock:
                self._inflight[idx].add(mid)
            try:
                framing.send_frame(sock, out, await_ack=False)
            except (OSError, ConnectionError):
                self._count_error(None)
                with self._lock:
                    self._inflight[idx].discard(mid)
                self.abandon(mid)
                self._teardown_conn(idx)

    def _reader(self, sock: socket.socket, idx: int) -> None:
        """Resolve shadow replies by the protocol's id echo — the pair's
        shadow side goes to the comparator (tagged with the candidate's
        rank); rejects abandon the pair."""
        while not self._closed.is_set():
            try:
                frame = bytes(
                    framing.recv_frame(
                        sock, send_ack=False, max_frame=MAX_REQUEST_FRAME
                    )
                )
                mid = protocol.frame_id(frame)
            except (OSError, ConnectionError, WireError):
                with self._lock:
                    lost = self._socks[idx] is sock
                if lost:
                    self._count_error(None)
                    self._teardown_conn(idx)
                return
            with self._lock:
                known = mid in self._inflight[idx]
                self._inflight[idx].discard(mid)
            if not known or self.compare is None:
                continue
            try:
                if protocol.is_reject(frame):
                    self.compare.abandon(mid)
                elif idx:
                    self.compare.note_shadow(
                        mid, float(protocol.parse_reply(frame)["prob"]), idx
                    )
                else:
                    # Two-arg form for rank 0: stub comparators predate
                    # the candidate-rank parameter.
                    self.compare.note_shadow(
                        mid, float(protocol.parse_reply(frame)["prob"])
                    )
            except (WireError, TypeError, ValueError):
                self._count_error(mid)
