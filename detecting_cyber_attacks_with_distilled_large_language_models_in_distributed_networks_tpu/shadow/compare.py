"""Online A/B comparison of serving vs shadow scores, pair by pair.

The mirror (shadow/mirror.py) produces two probabilities for one live
flow — the incumbent's and the candidate's, computed on the SAME bytes
at the same moment. This module pairs them by the router's mirror id and
turns the stream of pairs into the disagreement evidence the promotion
gate (shadow/gate.py) rules on:

* **flip rate** — the fraction of pairs whose thresholded prediction
  differs (the operator-facing "how often would the candidate have
  answered differently?");
* **mean |Δprob|** — the magnitude of score movement even when the
  decision held;
* **paired score histograms + PSI** — both sides binned on the SAME
  [0, 1] edges the drift monitor uses, with the candidate-vs-incumbent
  PSI (control/drift.py — one distance implementation repo-wide)
  catching distribution shifts that flips alone miss (a candidate that
  scores everything 0.1 hotter flips nothing near the extremes but has
  plainly drifted).

Every completed pair is one ATOMIC line on the paired-records JSONL
(obs/trace.py append discipline — concurrent writers can never
interleave partial lines), counted on ``fedtpu_shadow_pairs_total`` /
``fedtpu_shadow_flips_total``, and periodically folded into an atomic
``status.json`` (tmp + os.replace) — the cross-process surface the
controller's gate polls, so the comparator and the gate can live in
different processes exactly like the rest of the control plane
coordinates through the registry directory.

Pairing state is bounded: at ``max_pending`` half-open pairs the oldest
is dropped (counted) — a one-sided flood (shadow dead mid-burst, ejected
serving replicas) can never grow the dict without bound.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any

import numpy as np

from ..control.drift import psi
from ..obs import metrics as obs_metrics
from ..obs.trace import append_jsonl_line
from ..utils.logging import get_logger

log = get_logger()

#: Schema tag on every paired record, so stream consumers can reject
#: foreign JSONL lines when files get concatenated.
PAIR_SCHEMA = "fedtpu-shadow-v1"


def evaluate_status(
    status: dict,
    *,
    min_pairs: int,
    max_flip_rate: float,
    psi_threshold: float,
) -> tuple[bool, str]:
    """The gate's verdict arithmetic over one comparator snapshot — a
    pure function so the controller-side gate and in-process callers
    share ONE implementation. Fails closed: too few pairs is a refusal,
    and so is an uncomputable PSI."""
    pairs = int(status.get("pairs", 0) or 0)
    if pairs < int(min_pairs):
        return False, (
            f"insufficient evidence: {pairs} mirrored pair(s) < "
            f"min_pairs={min_pairs}"
        )
    flip_rate = float(status.get("flip_rate", 1.0))
    if flip_rate > float(max_flip_rate):
        return False, (
            f"live disagreement: flip_rate={flip_rate:.4f} > "
            f"{max_flip_rate} over {pairs} pair(s)"
        )
    d = status.get("psi")
    if d is None:
        return False, (
            f"live disagreement: paired-score PSI uncomputable over "
            f"{pairs} pair(s)"
        )
    if float(d) > float(psi_threshold):
        return False, (
            f"live disagreement: paired-score psi={float(d):.4f} > "
            f"{psi_threshold} over {pairs} pair(s)"
        )
    return True, (
        f"live agreement: flip_rate={flip_rate:.4f} <= {max_flip_rate}, "
        f"psi={float(d):.4f} <= {psi_threshold} over {pairs} pair(s)"
    )


class ShadowCompare:
    """Pair (serving_prob, shadow_prob) by mirror id; accumulate the
    disagreement statistics and publish them.

    Either side of a pair may arrive first (the shadow reply races the
    serving reply by construction); ``abandon`` sheds a pair whose other
    half can never arrive (reject, eject, dead shadow)."""

    def __init__(
        self,
        *,
        threshold: float = 0.5,
        bins: int = 10,
        pairs_jsonl: str | None = None,
        status_path: str | None = None,
        status_every: int = 8,
        max_pending: int = 8192,
        tracer=None,
        span_stride: int = 64,
        candidates: tuple[str, ...] = (),
    ):
        if not 0.0 < float(threshold) < 1.0:
            raise ValueError(f"threshold={threshold} must be in (0, 1)")
        if int(bins) < 2:
            raise ValueError(f"bins={bins} must be >= 2")
        self.threshold = float(threshold)
        self.pairs_jsonl = pairs_jsonl
        self.status_path = status_path
        self.status_every = max(1, int(status_every))
        self.max_pending = max(1, int(max_pending))
        self.tracer = tracer
        self._span_stride = max(1, int(span_stride))
        # Ranked candidate list (ISSUE 18): rank 0 is the GATED candidate
        # — the aggregate stats (what status.json and the gate rule on)
        # cover rank 0 only, so striding mirrored traffic across extra
        # candidates never dilutes the promotion verdict. Empty = the
        # single-candidate shape, where every pair is rank 0.
        self.candidates = tuple(str(c) for c in candidates)
        self._lock = threading.Lock()
        # Serializes write_status: two reply threads completing pairs
        # concurrently would share the per-pid tmp name, and the loser's
        # os.replace would find its tmp already consumed.
        self._status_lock = threading.Lock()
        # mid -> (side, prob, cand); insertion-ordered so overflow drops
        # oldest.
        self._open: dict[int, tuple[str, float, int]] = {}
        # mid -> request id (the serving tier's stamp), registered at
        # admission so completed pairs carry the join key the ground-
        # truth plane (labels/join.py) matches on. Bounded like _open.
        self._rids: dict[int, str] = {}
        # rank -> [pairs, flips] — per-candidate accounting.
        self._cand: dict[int, list[int]] = {}
        self._bins = int(bins)
        self._hist_serving = np.zeros(int(bins), np.int64)
        self._hist_shadow = np.zeros(int(bins), np.int64)
        self._pairs = 0
        self._flips = 0
        self._abs_dprob_sum = 0.0
        self._abandoned = 0
        self._pending_dropped = 0
        m = obs_metrics.default_registry()
        self._m_pairs = m.counter(
            "fedtpu_shadow_pairs_total",
            help="completed serving/shadow probability pairs",
        )
        self._m_flips = m.counter(
            "fedtpu_shadow_flips_total",
            help="pairs whose thresholded prediction disagreed",
        )

    # -------------------------------------------------------------- ingestion
    def register_rid(self, mid: int, rid: str) -> None:
        """Attach the live request's id (the serving tier's stamp) to a
        mirror id at admission, so the completed pair record carries the
        ground-truth join key. Bounded like the half-open dict."""
        with self._lock:
            if len(self._rids) >= 2 * self.max_pending:
                oldest = next(iter(self._rids))
                del self._rids[oldest]
            self._rids[int(mid)] = str(rid)

    def note_serving(self, mid: int, prob: float) -> None:
        self._note(mid, "serving", prob, 0)

    def note_shadow(self, mid: int, prob: float, cand: int = 0) -> None:
        """The shadow side of a pair; ``cand`` is the candidate's RANK
        when the mirror strides across a ranked list (0 = the gated
        candidate — the only rank the aggregate verdict counts)."""
        self._note(mid, "shadow", prob, int(cand))

    def abandon(self, mid: int) -> None:
        with self._lock:
            self._rids.pop(mid, None)
            if self._open.pop(mid, None) is not None:
                self._abandoned += 1

    def _note(self, mid: int, side: str, prob: float, cand: int) -> None:
        p = float(prob)
        rec = None
        with self._lock:
            other = self._open.get(mid)
            if other is None:
                if len(self._open) >= self.max_pending:
                    # Bounded half-open state: drop the OLDEST waiter —
                    # a one-sided flood must not grow memory unbounded.
                    oldest = next(iter(self._open))
                    del self._open[oldest]
                    self._rids.pop(oldest, None)
                    self._pending_dropped += 1
                self._open[mid] = (side, p, cand)
                return
            if other[0] == side:
                # Duplicate arrival on one side (a retried mirror send):
                # keep the first value, stay half-open.
                return
            del self._open[mid]
            rid = self._rids.pop(mid, None)
            serving = p if side == "serving" else other[1]
            shadow = p if side == "shadow" else other[1]
            # The pair's candidate rank rides the SHADOW side (the
            # serving side has no candidate identity).
            rank = cand if side == "shadow" else other[2]
            flip = (serving >= self.threshold) != (shadow >= self.threshold)
            cstat = self._cand.setdefault(rank, [0, 0])
            cstat[0] += 1
            if flip:
                cstat[1] += 1
            primary = rank == 0
            if primary:
                self._pairs += 1
                if flip:
                    self._flips += 1
                self._abs_dprob_sum += abs(serving - shadow)
                # Fixed [0, 1] bins: one multiply + clamp per scalar — the
                # np.histogram machinery is array-sized overkill on a path
                # that runs once per pair (p == 1.0 lands in the top bin,
                # matching the closed right edge everywhere else).
                self._hist_serving[
                    min(int(min(max(serving, 0.0), 1.0) * self._bins),
                        self._bins - 1)
                ] += 1
                self._hist_shadow[
                    min(int(min(max(shadow, 0.0), 1.0) * self._bins),
                        self._bins - 1)
                ] += 1
            pairs_now = self._pairs
            rec = {
                "schema": PAIR_SCHEMA,
                "mid": int(mid),
                "serving_prob": serving,
                "shadow_prob": shadow,
                "flip": int(flip),
            }
            if rid is not None:
                rec["rid"] = rid
            if rank:
                rec["cand"] = int(rank)
        self._m_pairs.inc()
        if rec["flip"]:
            self._m_flips.inc()
        if self.pairs_jsonl:
            try:
                append_jsonl_line(self.pairs_jsonl, json.dumps(rec))
            except OSError as e:
                log.warning(
                    f"[SHADOW] paired-record append failed (non-fatal): {e}"
                )
        if self.status_path and primary and (
            pairs_now % self.status_every == 0
        ):
            self.write_status()
        if self.tracer is not None and primary and (
            (pairs_now - 1) % self._span_stride == 0
        ):
            s = self.snapshot()
            self.tracer.record(
                "shadow-compare",
                t_start=time.time(),
                dur_s=0.0,
                pairs=s["pairs"],
                flip_rate=s["flip_rate"],
                psi=s["psi"],
                sampled_pairs=(
                    self._span_stride if self._span_stride > 1 else None
                ),
            )

    # --------------------------------------------------------------- verdict
    def snapshot(self) -> dict[str, Any]:
        """The current disagreement evidence (what status.json carries)."""
        with self._lock:
            pairs = self._pairs
            flips = self._flips
            dsum = self._abs_dprob_sum
            hs = self._hist_serving.copy()
            hd = self._hist_shadow.copy()
            abandoned = self._abandoned
            pending = len(self._open)
            pending_dropped = self._pending_dropped
            per_candidate = {
                str(rank): {
                    "candidate": (
                        self.candidates[rank]
                        if rank < len(self.candidates)
                        else None
                    ),
                    "pairs": c[0],
                    "flips": c[1],
                    "flip_rate": (c[1] / c[0]) if c[0] else 0.0,
                }
                for rank, c in sorted(self._cand.items())
            }
        d = None
        if pairs > 0 and hs.sum() > 0 and hd.sum() > 0:
            try:
                # Serving = expected, shadow = observed: "how far has the
                # candidate's score distribution moved off the incumbent's
                # on identical live flows" — the same PSI the drift
                # monitor speaks, so thresholds transfer.
                d = round(psi(hs, hd), 6)
            except ValueError:
                d = None
        return {
            "schema": PAIR_SCHEMA,
            "pairs": pairs,
            "flips": flips,
            "flip_rate": (flips / pairs) if pairs else 0.0,
            "mean_abs_dprob": (dsum / pairs) if pairs else 0.0,
            "psi": d,
            "threshold": self.threshold,
            "hist_serving": hs.tolist(),
            "hist_shadow": hd.tolist(),
            "abandoned": abandoned,
            "pending": pending,
            "pending_dropped": pending_dropped,
            "candidates": list(self.candidates),
            "per_candidate": per_candidate,
            "ts": time.time(),
        }

    def verdict(
        self,
        *,
        min_pairs: int,
        max_flip_rate: float,
        psi_threshold: float,
    ) -> tuple[bool, dict]:
        """(ok, verdict dict) over the CURRENT snapshot — the in-process
        shape of the gate's decision (the cross-process gate evaluates
        the same arithmetic over status.json)."""
        status = self.snapshot()
        ok, reason = evaluate_status(
            status,
            min_pairs=min_pairs,
            max_flip_rate=max_flip_rate,
            psi_threshold=psi_threshold,
        )
        return ok, {
            "ok": ok,
            "reason": reason,
            "pairs": status["pairs"],
            "flip_rate": round(status["flip_rate"], 6),
            "mean_abs_dprob": round(status["mean_abs_dprob"], 6),
            "psi": status["psi"],
            "min_pairs": int(min_pairs),
            "max_flip_rate": float(max_flip_rate),
            "psi_threshold": float(psi_threshold),
        }

    def write_status(self) -> None:
        """Atomically publish the snapshot (tmp + os.replace): a gate
        polling from another process sees the old status or the new one,
        never a torn write."""
        if not self.status_path:
            return
        snap = self.snapshot()
        tmp = f"{self.status_path}.tmp.{os.getpid()}"
        with self._status_lock:
            try:
                os.makedirs(
                    os.path.dirname(self.status_path) or ".", exist_ok=True
                )
                with open(tmp, "w") as f:
                    json.dump(snap, f)
                os.replace(tmp, self.status_path)
            except OSError as e:
                log.warning(
                    f"[SHADOW] status write failed (non-fatal): {e}"
                )
