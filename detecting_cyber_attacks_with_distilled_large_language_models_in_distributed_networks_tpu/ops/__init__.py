"""Kernel/device ops. Re-exports are lazy (PEP 562): ``ops.fold`` is
imported by the jax-free comm server tier, and an eager ``from
.attention import ...`` here would drag jax (seconds of import, a
device runtime) into every aggregation-only process."""

_ATTENTION = ("dot_product_attention", "make_attention_bias")
_METRICS = ("BinaryCounts", "binary_counts", "finalize_metrics")

__all__ = [*_ATTENTION, *_METRICS]


def __getattr__(name):
    if name in _ATTENTION:
        from . import attention

        return getattr(attention, name)
    if name in _METRICS:
        from . import metrics

        return getattr(metrics, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
