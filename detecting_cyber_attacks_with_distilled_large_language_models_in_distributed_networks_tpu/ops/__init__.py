from .attention import dot_product_attention, make_attention_bias  # noqa: F401
from .metrics import (  # noqa: F401
    BinaryCounts,
    binary_counts,
    finalize_metrics,
)
