"""Attention ops.

The reference's attention is whatever HF ``DistilBertModel`` does inside
PyTorch (reference client1.py:61). Here it is explicit and TPU-shaped:

* ``dot``   — einsum attention; XLA fuses mask+softmax+matmul chains onto the
              MXU. Scores/softmax run in fp32 even under bf16 activations.
* ``flash`` — Pallas blocked flash-attention kernel (ops/flash_attention.py),
              O(L) memory, VMEM-tiled.
* ``ring``  — sequence-parallel blockwise attention over a mesh axis
              (parallel/ring_attention.py) for long-context.

All variants consume the same ``[B, H, L, D]`` tensors and an additive bias.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e9  # large-negative mask addend; safe in fp32 softmax


def make_attention_bias(attention_mask: jnp.ndarray, dtype=jnp.float32) -> jnp.ndarray:
    """``[B, L]`` 0/1 mask -> additive ``[B, 1, 1, L]`` bias (0 keep, -1e9 drop).

    Matches HF DistilBERT's masked_fill of key positions where mask==0.
    """
    bias = (1.0 - attention_mask.astype(dtype)) * NEG_INF
    return bias[:, None, None, :]


def dot_product_attention(
    q: jnp.ndarray,  # [B, H, Lq, D]
    k: jnp.ndarray,  # [B, H, Lk, D]
    v: jnp.ndarray,  # [B, H, Lk, D]
    bias: jnp.ndarray | None = None,  # additive, broadcastable to [B, H, Lq, Lk]
    *,
    dropout_rate: float = 0.0,
    dropout_rng: jax.Array | None = None,
    deterministic: bool = True,
) -> jnp.ndarray:
    """Scaled dot-product attention with fp32 softmax.

    Scores accumulate in fp32 on the MXU (``preferred_element_type``) so bf16
    activations don't lose the softmax; output returns to q's dtype.
    """
    depth = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(depth, jnp.float32))
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    weights = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if dropout_rate > 0.0 and not deterministic:
        # Mask AFTER the compute-dtype cast: the [B,H,L,L] keep-mask
        # multiply then runs at activation width (half the HBM traffic of
        # an fp32 apply); the mask is 0-or-1/(1-p) noise either way.
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, weights.shape)
        weights = weights * keep.astype(q.dtype) * (1.0 / (1.0 - dropout_rate))
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v)
