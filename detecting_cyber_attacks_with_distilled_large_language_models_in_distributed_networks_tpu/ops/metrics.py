"""On-device classification metrics.

The reference accumulates predictions on the host and calls sklearn per eval
(reference client1.py:118-150: ``precision_recall_fscore_support``,
``confusion_matrix``). Here the eval step accumulates sufficient statistics
(loss sum, correct count, TP/FP/FN/TN) on device — one scalar pytree per
batch, no [N]-sized host transfers — and the host finalizes the same five
metrics (Accuracy, Loss, Precision, Recall, F1) plus the confusion matrix.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np


class BinaryCounts(NamedTuple):
    """Sufficient statistics for binary classification metrics."""

    loss_sum: jnp.ndarray  # fp32 scalar — sum of per-batch mean losses
    n_batches: jnp.ndarray  # fp32 scalar
    n_examples: jnp.ndarray  # fp32 scalar
    correct: jnp.ndarray  # fp32 scalar
    tp: jnp.ndarray
    fp: jnp.ndarray
    fn: jnp.ndarray
    tn: jnp.ndarray

    @classmethod
    def zero(cls) -> "BinaryCounts":
        z = jnp.zeros((), jnp.float32)
        return cls(z, z, z, z, z, z, z, z)

    def __add__(self, other: "BinaryCounts") -> "BinaryCounts":  # type: ignore[override]
        return BinaryCounts(*(a + b for a, b in zip(self, other)))


def binary_counts(
    logits: jnp.ndarray,  # [B, 2]
    labels: jnp.ndarray,  # [B]
    loss: jnp.ndarray,  # scalar — batch mean loss
    valid: jnp.ndarray | None = None,  # [B] 0/1 — padded-row mask
) -> BinaryCounts:
    preds = jnp.argmax(logits, axis=-1)
    if valid is None:
        valid = jnp.ones_like(labels)
    v = valid.astype(jnp.float32)
    pos = (labels == 1).astype(jnp.float32) * v
    neg = (labels == 0).astype(jnp.float32) * v
    pred_pos = (preds == 1).astype(jnp.float32)
    pred_neg = (preds == 0).astype(jnp.float32)
    has_valid = (v.sum() > 0).astype(jnp.float32)
    return BinaryCounts(
        # All-padding batches (possible when clients' eval splits are stacked
        # to a common length) must not dilute the batch-mean loss.
        loss_sum=loss.astype(jnp.float32) * has_valid,
        n_batches=has_valid,
        n_examples=v.sum(),
        correct=((preds == labels).astype(jnp.float32) * v).sum(),
        tp=(pos * pred_pos).sum(),
        fp=(neg * pred_pos).sum(),
        fn=(pos * pred_neg).sum(),
        tn=(neg * pred_neg).sum(),
    )


def finalize_metrics(counts: BinaryCounts) -> dict[str, float]:
    """Host-side finalization into the reference's five-metric schema
    (Accuracy in percent, as at reference client1.py:143) + confusion matrix.

    Precision/recall/F1 follow sklearn's ``average='binary'`` zero-division
    convention (0.0 when undefined)."""
    c = {k: float(v) for k, v in counts._asdict().items()}
    n = max(c["n_examples"], 1.0)
    precision = c["tp"] / (c["tp"] + c["fp"]) if (c["tp"] + c["fp"]) > 0 else 0.0
    recall = c["tp"] / (c["tp"] + c["fn"]) if (c["tp"] + c["fn"]) > 0 else 0.0
    f1 = (
        2 * precision * recall / (precision + recall)
        if (precision + recall) > 0
        else 0.0
    )
    return {
        "Accuracy": 100.0 * c["correct"] / n,
        "Loss": c["loss_sum"] / max(c["n_batches"], 1.0),
        "Precision": precision,
        "Recall": recall,
        "F1-Score": f1,
        "confusion_matrix": np.array(
            [[c["tn"], c["fp"]], [c["fn"], c["tp"]]], dtype=np.int64
        ),
        "n": int(c["n_examples"]),
    }
