"""On-device classification metrics.

The reference accumulates predictions on the host and calls sklearn per eval
(reference client1.py:118-150: ``precision_recall_fscore_support``,
``confusion_matrix``). Here the eval step accumulates sufficient statistics
(loss sum, correct count, TP/FP/FN/TN) on device — one scalar pytree per
batch, no [N]-sized host transfers — and the host finalizes the same five
metrics (Accuracy, Loss, Precision, Recall, F1) plus the confusion matrix.

The K-class plane (ISSUE 18) generalizes the same discipline: a
:class:`ClassCounts` carries a dense [K, K] confusion matrix (rows =
truth, cols = prediction) instead of four scalars, and
:func:`finalize_class_metrics` renders macro-averaged P/R/F1 plus
per-class support. K = 2 is NOT a parallel implementation — it routes
through the binary kernels verbatim, so the multi-class path is
bit-identical to the binary one on the same inputs (the crc contract
bench.py's labels arm pins).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np


class BinaryCounts(NamedTuple):
    """Sufficient statistics for binary classification metrics."""

    loss_sum: jnp.ndarray  # fp32 scalar — sum of per-batch mean losses
    n_batches: jnp.ndarray  # fp32 scalar
    n_examples: jnp.ndarray  # fp32 scalar
    correct: jnp.ndarray  # fp32 scalar
    tp: jnp.ndarray
    fp: jnp.ndarray
    fn: jnp.ndarray
    tn: jnp.ndarray

    @classmethod
    def zero(cls) -> "BinaryCounts":
        z = jnp.zeros((), jnp.float32)
        return cls(z, z, z, z, z, z, z, z)

    def __add__(self, other: "BinaryCounts") -> "BinaryCounts":  # type: ignore[override]
        return BinaryCounts(*(a + b for a, b in zip(self, other)))


def binary_counts(
    logits: jnp.ndarray,  # [B, 2]
    labels: jnp.ndarray,  # [B]
    loss: jnp.ndarray,  # scalar — batch mean loss
    valid: jnp.ndarray | None = None,  # [B] 0/1 — padded-row mask
) -> BinaryCounts:
    preds = jnp.argmax(logits, axis=-1)
    if valid is None:
        valid = jnp.ones_like(labels)
    v = valid.astype(jnp.float32)
    pos = (labels == 1).astype(jnp.float32) * v
    neg = (labels == 0).astype(jnp.float32) * v
    pred_pos = (preds == 1).astype(jnp.float32)
    pred_neg = (preds == 0).astype(jnp.float32)
    has_valid = (v.sum() > 0).astype(jnp.float32)
    return BinaryCounts(
        # All-padding batches (possible when clients' eval splits are stacked
        # to a common length) must not dilute the batch-mean loss.
        loss_sum=loss.astype(jnp.float32) * has_valid,
        n_batches=has_valid,
        n_examples=v.sum(),
        correct=((preds == labels).astype(jnp.float32) * v).sum(),
        tp=(pos * pred_pos).sum(),
        fp=(neg * pred_pos).sum(),
        fn=(pos * pred_neg).sum(),
        tn=(neg * pred_neg).sum(),
    )


def finalize_metrics(counts: BinaryCounts) -> dict[str, float]:
    """Host-side finalization into the reference's five-metric schema
    (Accuracy in percent, as at reference client1.py:143) + confusion matrix.

    Precision/recall/F1 follow sklearn's ``average='binary'`` zero-division
    convention (0.0 when undefined)."""
    c = {k: float(v) for k, v in counts._asdict().items()}
    n = max(c["n_examples"], 1.0)
    precision = c["tp"] / (c["tp"] + c["fp"]) if (c["tp"] + c["fp"]) > 0 else 0.0
    recall = c["tp"] / (c["tp"] + c["fn"]) if (c["tp"] + c["fn"]) > 0 else 0.0
    f1 = (
        2 * precision * recall / (precision + recall)
        if (precision + recall) > 0
        else 0.0
    )
    return {
        "Accuracy": 100.0 * c["correct"] / n,
        "Loss": c["loss_sum"] / max(c["n_batches"], 1.0),
        "Precision": precision,
        "Recall": recall,
        "F1-Score": f1,
        "confusion_matrix": np.array(
            [[c["tn"], c["fp"]], [c["fn"], c["tp"]]], dtype=np.int64
        ),
        "n": int(c["n_examples"]),
    }


# ------------------------------------------------------------- K classes
class ClassCounts(NamedTuple):
    """Sufficient statistics for K-class classification metrics.

    ``cm`` is the dense [K, K] confusion matrix, rows = truth, cols =
    prediction — the full sufficient statistic for every count-derived
    metric, still O(K^2) scalars per eval instead of [N]-sized host
    transfers."""

    loss_sum: jnp.ndarray  # fp32 scalar — sum of per-batch mean losses
    n_batches: jnp.ndarray  # fp32 scalar
    n_examples: jnp.ndarray  # fp32 scalar
    correct: jnp.ndarray  # fp32 scalar
    cm: jnp.ndarray  # [K, K] fp32 — rows truth, cols prediction

    @classmethod
    def zero(cls, n_classes: int) -> "ClassCounts":
        z = jnp.zeros((), jnp.float32)
        return cls(z, z, z, z, jnp.zeros((n_classes, n_classes), jnp.float32))

    def __add__(self, other: "ClassCounts") -> "ClassCounts":  # type: ignore[override]
        return ClassCounts(*(a + b for a, b in zip(self, other)))


def class_counts(
    logits: jnp.ndarray,  # [B, K]
    labels: jnp.ndarray,  # [B]
    loss: jnp.ndarray,  # scalar — batch mean loss
    valid: jnp.ndarray | None = None,  # [B] 0/1 — padded-row mask
) -> ClassCounts:
    """K-class sufficient statistics. K = 2 routes through
    :func:`binary_counts` verbatim and reassembles its four scalars into
    the [2, 2] matrix — bit-identical to the binary path by
    construction, not by accident of arithmetic."""
    k = int(logits.shape[-1])
    if k == 2:
        b = binary_counts(logits, labels, loss, valid)
        return ClassCounts(
            loss_sum=b.loss_sum,
            n_batches=b.n_batches,
            n_examples=b.n_examples,
            correct=b.correct,
            cm=jnp.stack(
                [jnp.stack([b.tn, b.fp]), jnp.stack([b.fn, b.tp])]
            ),
        )
    preds = jnp.argmax(logits, axis=-1)
    if valid is None:
        valid = jnp.ones_like(labels)
    v = valid.astype(jnp.float32)
    classes = jnp.arange(k)
    # One-hot contraction: cm[t, p] = sum_b valid_b [label_b==t][pred_b==p].
    oh_true = (labels[:, None] == classes[None, :]).astype(jnp.float32)
    oh_pred = (preds[:, None] == classes[None, :]).astype(jnp.float32)
    cm = (oh_true * v[:, None]).T @ oh_pred
    has_valid = (v.sum() > 0).astype(jnp.float32)
    return ClassCounts(
        loss_sum=loss.astype(jnp.float32) * has_valid,
        n_batches=has_valid,
        n_examples=v.sum(),
        correct=((preds == labels).astype(jnp.float32) * v).sum(),
        cm=cm,
    )


def finalize_class_metrics(counts: ClassCounts) -> dict[str, float]:
    """Host-side K-class finalization.

    K = 2 delegates to :func:`finalize_metrics` over the reassembled
    :class:`BinaryCounts` — the SAME float arithmetic, so the rendered
    dict is bit-identical to the binary path's. K > 2 renders the same
    five-metric schema with macro-averaged Precision/Recall/F1 (sklearn
    ``average='macro'`` with zero-division -> 0.0) plus ``per_class``
    recall/support rows keyed by class index."""
    cm = np.asarray(counts.cm, dtype=np.float64)
    k = cm.shape[0]
    if k == 2:
        return finalize_metrics(
            BinaryCounts(
                loss_sum=counts.loss_sum,
                n_batches=counts.n_batches,
                n_examples=counts.n_examples,
                correct=counts.correct,
                tp=counts.cm[1, 1],
                fp=counts.cm[0, 1],
                fn=counts.cm[1, 0],
                tn=counts.cm[0, 0],
            )
        )
    n = max(float(counts.n_examples), 1.0)
    diag = np.diag(cm)
    pred_tot = cm.sum(axis=0)  # column sums: predicted-as-c
    true_tot = cm.sum(axis=1)  # row sums: truly-c (support)
    with np.errstate(invalid="ignore", divide="ignore"):
        prec = np.where(pred_tot > 0, diag / np.maximum(pred_tot, 1.0), 0.0)
        rec = np.where(true_tot > 0, diag / np.maximum(true_tot, 1.0), 0.0)
        denom = prec + rec
        f1 = np.where(denom > 0, 2 * prec * rec / np.maximum(denom, 1e-38), 0.0)
    return {
        "Accuracy": 100.0 * float(counts.correct) / n,
        "Loss": float(counts.loss_sum) / max(float(counts.n_batches), 1.0),
        "Precision": float(prec.mean()),
        "Recall": float(rec.mean()),
        "F1-Score": float(f1.mean()),
        "confusion_matrix": cm.astype(np.int64),
        "per_class": {
            str(c): {
                "precision": float(prec[c]),
                "recall": float(rec[c]),
                "f1": float(f1[c]),
                "support": int(true_tot[c]),
            }
            for c in range(k)
        },
        "n": int(float(counts.n_examples)),
        "n_classes": k,
    }
