"""Pallas blocked flash attention for TPU — forward AND backward kernels.

Forward: the [Lq, Lk] score matrix is never materialized in HBM — each grid
step streams one query block against key/value blocks held in VMEM,
maintaining the online-softmax running max/denominator (the standard flash
recurrence), with fp32 accumulation feeding the MXU. Memory is O(L·D) per
(batch, head) instead of O(L²). The kernel also emits the per-row
logsumexp, the residual the backward needs.

Backward: two Pallas kernels (the Dao et al. split) recompute score tiles
on the fly from (q, k, bias, lse) — O(L²) values exist only transiently in
VMEM tiles, never in HBM:

* dK/dV kernel — grid over key blocks; each instance streams query blocks,
  accumulating ``dv += pᵀ·dO`` and ``dk += dsᵀ·q`` (plus the key-bias
  gradient rows);
* dQ kernel — grid over query blocks; each instance streams key blocks,
  accumulating ``dq += ds·k``.

The softmax-jacobian correction uses ``delta = rowsum(dO ⊙ O)`` (computed
in XLA — O(L·D)), which is exact with or without dropout since the output
is always ``weights @ v``.

Attention dropout: supported in both directions via a counter-based hash
(murmur-style finalizer) over the GLOBAL (batch, head, q, k) position and
a per-call seed — forward and backward regenerate identical keep masks
from the same coordinates, so nothing L² is ever stored. The hash is plain
integer jnp arithmetic, so it runs identically under the CPU interpreter
and the TPU lowering. (The dot path draws its mask from
``jax.random.bernoulli`` instead, so flash-with-dropout matches the dot
path in distribution, not bitwise.)

The reference has no analogue — its attention is whatever torch runs inside
HF ``DistilBertModel`` (reference client1.py:61). At the reference's L=128
XLA's fused dot attention is already fine; this kernel is the long-context
headroom path (``ModelConfig.attention_impl="flash"``) and the building
block the ring-attention sequence-parallel path composes with.

Bias: only key-position masks — shape ``[B, 1, 1, Lk]`` additive, as produced
by ``ops.attention.make_attention_bias`` — are supported.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Measured sweet spot on TPU v5e (B=8, H=12, D=64, L=2048): (256, 512) runs
# 2.3x faster than (128, 128) — bigger K blocks amortize the per-matmul MXU
# ramp — and overtakes XLA's fused dot attention from L~2048. Shorter
# sequences clamp to L automatically.
DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 512

# Smallest blocks worth running as a Pallas grid. A whole-length single
# block is always fine (block == array dim); otherwise blocks below the TPU
# sublane/lane tile (8 query rows, 128 key columns) would lower poorly and
# a gcd-degenerate fit (e.g. prime L -> block 1) would build a pathological
# grid — those lengths take the XLA dot path instead (see flash_attention).
MIN_BLOCK_Q = 8
MIN_BLOCK_K = 128


def _fit(block: int, length: int) -> int:
    """Largest block <= the requested size that tiles ``length``: short
    sequences clamp to L, and lengths that aren't multiples of the
    default (e.g. 384 vs 512) snap to gcd."""
    if length <= block:
        return length
    import math

    return math.gcd(length, block)


def fits_blocks(lq: int, lk: int, block_q: int, block_k: int) -> bool:
    """Whether (lq, lk) tile into viable Pallas blocks for these requests.

    A block exactly as requested, or covering the whole length, is always
    viable (explicit small blocks are the caller's choice — tests use them
    under interpret mode); only a gcd fit that SHRANK the request below the
    TPU tile minimum is degenerate."""

    def ok(length: int, block: int, min_block: int) -> bool:
        fit = _fit(block, length)
        return fit == block or fit == length or fit >= min_block

    return ok(lq, block_q, MIN_BLOCK_Q) and ok(lk, block_k, MIN_BLOCK_K)


def _keep_mask(seed, b, h, q0, k0, bq: int, bk: int, rate: float):
    """Deterministic [bq, bk] fp32 keep mask for dropout, from a hash of
    the GLOBAL (seed, batch, head, q index, k index) coordinate — the
    forward and both backward kernels regenerate the identical mask from
    the same coordinates, whatever their block iteration order.

    ``seed`` is a pair of uint32 words (64 bits total): a single 32-bit
    seed would birthday-collide to an identical whole-call mask after
    ~2^16 distinct dropout_rng draws (steps x layers)."""
    # Everything MUST be uint32 before the mixing ops: a traced int32
    # (program_id, block offsets) would silently promote the whole chain
    # to a signed dtype, turning the >> shifts arithmetic and changing the
    # bits between call sites.
    q0 = jnp.asarray(q0).astype(jnp.uint32)
    k0 = jnp.asarray(k0).astype(jnp.uint32)
    s0 = jnp.asarray(seed[0]).astype(jnp.uint32)
    s1 = jnp.asarray(seed[1]).astype(jnp.uint32)
    qi = q0 + jax.lax.broadcasted_iota(jnp.uint32, (bq, bk), 0)
    ki = k0 + jax.lax.broadcasted_iota(jnp.uint32, (bq, bk), 1)
    x = (qi * jnp.uint32(0x9E3779B1)) ^ (ki * jnp.uint32(0x85EBCA77))
    x = x ^ (
        s0
        + jnp.asarray(b).astype(jnp.uint32) * jnp.uint32(0xC2B2AE3D)
        + jnp.asarray(h).astype(jnp.uint32) * jnp.uint32(0x27D4EB2F)
    )
    # Fold the second seed word in with its own odd multiplier so the two
    # words act as one 64-bit seed rather than xor-cancelling.
    x = x + s1 * jnp.uint32(0x632BE59B)
    # murmur3 finalizer: avalanche the combined coordinate.
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    # Threshold in integer space (x uniform over [0, 2^32)): Mosaic has no
    # uint32 -> float cast, and none is needed — keep iff x >= rate * 2^32.
    thresh = jnp.uint32(min(2**32 - 1, int(round(rate * 4294967296.0))))
    return (x >= thresh).astype(jnp.float32)


def _fwd_kernel(
    q_ref, k_ref, v_ref, bias_ref, seed_ref, o_ref, lse_ref,
    *, scale: float, block_k: int, rate: float,
):
    """One query block vs. all key blocks, online softmax (+ dropout).

    Matmul inputs stay in the activation dtype (bf16 on TPU) with fp32 MXU
    accumulation — full MXU rate, and the same numerics as the dot path
    (ops/attention.py feeds bf16 into its einsums the same way). Softmax
    statistics and the accumulator are fp32.
    """
    q = q_ref[0, 0]  # [bq, D], activation dtype
    bq = q.shape[0]
    d = v_ref.shape[-1]
    lk = k_ref.shape[2]
    num_kb = lk // block_k
    b, h, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    seed = (seed_ref[0, 0], seed_ref[0, 1])
    inv = 1.0 / (1.0 - rate) if rate else 1.0

    def body(i, carry):
        acc, m, l = carry
        k_blk = k_ref[0, 0, pl.ds(i * block_k, block_k), :]
        v_blk = v_ref[0, 0, pl.ds(i * block_k, block_k), :]
        b_blk = bias_ref[0, 0, pl.ds(i * block_k, block_k)].astype(jnp.float32)
        s = (
            jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
            + b_blk[None, :]
        )  # [bq, bk] fp32
        m_new = jnp.maximum(m, s.max(axis=1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        # The denominator accumulates the UNdropped p (softmax semantics);
        # dropout applies to the normalized weights, i.e. to p here since
        # the normalization divides at the end.
        l_new = l * alpha + p.sum(axis=1)
        if rate:
            keep = _keep_mask(
                seed, b, h, qi * bq, i * block_k, bq, block_k, rate
            )
            p = p * keep * inv
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, num_kb, body, (acc0, m0, l0))
    # -1e9 mask addends keep l > 0 even for fully masked rows (matches the
    # dot-attention path, which softmaxes the same finite scores).
    o_ref[0, 0] = (acc / l[:, None]).astype(o_ref.dtype)
    lse_ref[0, 0, :, 0] = m + jnp.log(l)


def _dkdv_kernel(
    q_ref, k_ref, v_ref, bias_ref, lse_ref, delta_ref, do_ref, seed_ref,
    dk_ref, dv_ref, db_ref,
    *, scale: float, block_q: int, rate: float,
):
    """One key block vs. all query blocks: accumulate dk, dv, and this
    head's key-bias gradient rows. Score tiles are recomputed from
    (q, k, bias, lse) — fp32 throughout (the XLA recompute backward this
    replaces also ran fp32; grads match the dot path's numerics)."""
    k_blk = k_ref[0, 0].astype(jnp.float32)  # [bk, D]
    v_blk = v_ref[0, 0].astype(jnp.float32)
    bias_blk = bias_ref[0, 0].astype(jnp.float32)  # [bk]
    bk, d = k_blk.shape
    lq = q_ref.shape[2]
    num_qb = lq // block_q
    b, h, ki = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    seed = (seed_ref[0, 0], seed_ref[0, 1])
    inv = 1.0 / (1.0 - rate) if rate else 1.0

    def body(i, carry):
        dk_acc, dv_acc, db_acc = carry
        qb = q_ref[0, 0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        dob = do_ref[0, 0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q), 0]  # [bq]
        dlt = delta_ref[0, 0, pl.ds(i * block_q, block_q), 0]
        s = (
            jax.lax.dot_general(
                qb, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
            + bias_blk[None, :]
        )  # [bq, bk]
        p = jnp.exp(s - lse[:, None])  # normalized weights (softmax rows)
        dpn = jax.lax.dot_general(
            dob, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk] = dO @ vᵀ
        if rate:
            keep = _keep_mask(
                seed, b, h, i * block_q, ki * bk, block_q, bk, rate
            )
            y = p * keep * inv  # dropped weights (what multiplied v)
            dpn = dpn * keep * inv
        else:
            y = p
        ds = p * (dpn - dlt[:, None])  # softmax jacobian
        dv_acc = dv_acc + jax.lax.dot_general(
            y, dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # yᵀ @ dO -> [bk, D]
        dk_acc = dk_acc + scale * jax.lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # dsᵀ @ q -> [bk, D]
        db_acc = db_acc + ds.sum(axis=0)  # [bk]
        return dk_acc, dv_acc, db_acc

    z = jnp.zeros((bk, d), jnp.float32)
    dk, dv, db = jax.lax.fori_loop(0, num_qb, body, (z, z, jnp.zeros((bk,), jnp.float32)))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)
    db_ref[0, 0, :, 0] = db


def _dq_kernel(
    q_ref, k_ref, v_ref, bias_ref, lse_ref, delta_ref, do_ref, seed_ref,
    dq_ref,
    *, scale: float, block_k: int, rate: float,
):
    """One query block vs. all key blocks: accumulate dq."""
    qb = q_ref[0, 0].astype(jnp.float32)  # [bq, D]
    dob = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0, :, 0]  # [bq]
    dlt = delta_ref[0, 0, :, 0]
    bq, d = qb.shape
    lk = k_ref.shape[2]
    num_kb = lk // block_k
    b, h, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    seed = (seed_ref[0, 0], seed_ref[0, 1])
    inv = 1.0 / (1.0 - rate) if rate else 1.0

    def body(i, dq_acc):
        k_blk = k_ref[0, 0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        bias_blk = bias_ref[0, 0, pl.ds(i * block_k, block_k)].astype(jnp.float32)
        s = (
            jax.lax.dot_general(
                qb, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
            + bias_blk[None, :]
        )
        p = jnp.exp(s - lse[:, None])
        dpn = jax.lax.dot_general(
            dob, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if rate:
            keep = _keep_mask(
                seed, b, h, qi * bq, i * block_k, bq, block_k, rate
            )
            dpn = dpn * keep * inv
        ds = p * (dpn - dlt[:, None])
        return dq_acc + scale * jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dq = jax.lax.fori_loop(0, num_kb, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _key_bias(bias: jnp.ndarray | None, batch: int, lk: int) -> jnp.ndarray:
    """Returns [B, 1, Lk]: the middle singleton keeps the Pallas block's
    second-to-last dim equal to the array dim (the TPU lowering requires
    last-two block dims divisible by (8, 128) or equal to the array's)."""
    if bias is None:
        return jnp.zeros((batch, 1, lk), jnp.float32)
    if bias.ndim != 4 or bias.shape[1] != 1 or bias.shape[2] != 1:
        raise ValueError(
            f"flash_attention supports key-position bias [B,1,1,Lk] only, got {bias.shape}"
        )
    return bias[:, 0, :, :].astype(jnp.float32)


def _flash_forward(
    q, k, v, bias, seed, *, rate: float, block_q: int, block_k: int, interpret: bool
):
    b, h, lq, d = q.shape
    lk = k.shape[2]
    block_q = _fit(block_q, lq)
    block_k = _fit(block_k, lk)
    key_bias = _key_bias(bias, b, lk)
    scale = 1.0 / (d**0.5)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_k=block_k, rate=rate
    )
    return pl.pallas_call(
        kernel,
        grid=(b, h, lq // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, lk, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, lk, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, lk), lambda bi, hi, qi: (bi, 0, 0)),
            pl.BlockSpec((1, 2), lambda bi, hi, qi: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, lq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, key_bias, seed)


def _flash_backward(
    q, k, v, bias, seed, out, lse, do,
    *, rate: float, block_q: int, block_k: int, interpret: bool,
):
    b, h, lq, d = q.shape
    lk = k.shape[2]
    block_q = _fit(block_q, lq)
    block_k = _fit(block_k, lk)
    key_bias = _key_bias(bias, b, lk)
    scale = 1.0 / (d**0.5)
    # delta = rowsum(dO ⊙ O): O(L·D) in XLA; exact with or without dropout.
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )[..., None]  # [B, H, Lq, 1]

    full_q = pl.BlockSpec((1, 1, lq, d), lambda bi, hi, i: (bi, hi, 0, 0))
    full_k = pl.BlockSpec((1, 1, lk, d), lambda bi, hi, i: (bi, hi, 0, 0))
    blk_q = pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, i: (bi, hi, i, 0))
    blk_k = pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, i: (bi, hi, i, 0))
    full_rows = pl.BlockSpec((1, 1, lq, 1), lambda bi, hi, i: (bi, hi, 0, 0))
    blk_rows = pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, i: (bi, hi, i, 0))
    full_bias = pl.BlockSpec((1, 1, lk), lambda bi, hi, i: (bi, 0, 0))
    blk_bias = pl.BlockSpec((1, 1, block_k), lambda bi, hi, i: (bi, 0, i))
    seed_spec = pl.BlockSpec((1, 2), lambda bi, hi, i: (0, 0))

    dk, dv, db_h = pl.pallas_call(
        functools.partial(
            _dkdv_kernel, scale=scale, block_q=block_q, rate=rate
        ),
        grid=(b, h, lk // block_k),
        in_specs=[full_q, blk_k, blk_k, blk_bias, full_rows, full_rows, full_q, seed_spec],
        out_specs=[
            blk_k,
            blk_k,
            pl.BlockSpec((1, 1, block_k, 1), lambda bi, hi, i: (bi, hi, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
            jax.ShapeDtypeStruct((b, h, lk, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, key_bias, lse, delta, do, seed)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, block_k=block_k, rate=rate),
        grid=(b, h, lq // block_q),
        in_specs=[blk_q, full_k, full_k, full_bias, blk_rows, blk_rows, blk_q, seed_spec],
        out_specs=blk_q,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v, key_bias, lse, delta, do, seed)

    dbias = None
    if bias is not None:
        # [B, H, Lk, 1] per-head rows -> the key-position bias layout.
        dbias = db_h[..., 0].sum(axis=1)[:, None, None, :].astype(bias.dtype)
    return dq, dk, dv, dbias


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash(q, k, v, bias, seed, rate, block_q, block_k, interpret):
    out, _ = _flash_forward(
        q, k, v, bias, seed,
        rate=rate, block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return out


def _flash_fwd(q, k, v, bias, seed, rate, block_q, block_k, interpret):
    out, lse = _flash_forward(
        q, k, v, bias, seed,
        rate=rate, block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return out, (q, k, v, bias, seed, out, lse)


def _flash_bwd(rate, block_q, block_k, interpret, res, do):
    q, k, v, bias, seed, out, lse = res
    dq, dk, dv, dbias = _flash_backward(
        q, k, v, bias, seed, out, lse, do,
        rate=rate, block_q=block_q, block_k=block_k, interpret=interpret,
    )
    dseed = np.zeros(seed.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, dbias, dseed


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,  # [B, H, Lq, D]
    k: jnp.ndarray,  # [B, H, Lk, D]
    v: jnp.ndarray,  # [B, H, Lk, D]
    bias: jnp.ndarray | None = None,  # [B, 1, 1, Lk] additive key mask
    *,
    dropout_rate: float = 0.0,
    dropout_rng: jax.Array | None = None,
    deterministic: bool = True,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Blocked flash attention; drop-in for ``dot_product_attention``,
    including attention dropout (hash-based masks — same distribution as
    the dot path, different bits). ``interpret=None`` auto-selects
    interpreter mode off TPU so the same tests run on the CPU mesh.

    Lengths whose gcd with the requested blocks is degenerate (prime or odd
    L — block 1 would mean an Lq-step grid) fall back to the XLA dot path,
    which is faster than a shredded Pallas grid at any such length."""
    rate = 0.0
    if dropout_rate > 0.0 and not deterministic:
        if dropout_rng is None:
            raise ValueError("flash attention dropout needs dropout_rng")
        rate = float(dropout_rate)
    if not fits_blocks(q.shape[2], k.shape[2], block_q, block_k):
        from .attention import dot_product_attention

        return dot_product_attention(
            q, k, v, bias,
            dropout_rate=dropout_rate,
            dropout_rng=dropout_rng,
            deterministic=deterministic,
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if rate:
        seed = jax.random.bits(dropout_rng, (1, 2), jnp.uint32)
    else:
        seed = jnp.zeros((1, 2), jnp.uint32)
    return _flash(q, k, v, bias, seed, rate, block_q, block_k, interpret)
