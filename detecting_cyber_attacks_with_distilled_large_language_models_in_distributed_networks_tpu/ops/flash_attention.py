"""Pallas blocked flash attention for TPU.

Forward pass is a Pallas kernel: the [Lq, Lk] score matrix is never
materialized in HBM — each grid step streams one query block against key/value
blocks held in VMEM, maintaining the online-softmax running max/denominator
(the standard flash recurrence), with fp32 accumulation feeding the MXU.
Memory is O(L·D) per (batch, head) instead of O(L²).

The reference has no analogue — its attention is whatever torch runs inside
HF ``DistilBertModel`` (reference client1.py:61). At the reference's L=128
XLA's fused dot attention is already fine; this kernel is the long-context
headroom path (``ModelConfig.attention_impl="flash"``) and the building
block the ring-attention sequence-parallel path composes with.

Differentiability: ``flash_attention`` carries a ``jax.custom_vjp`` whose
backward recomputes the softmax with standard XLA ops (O(L²) scores live only
inside the backward). Forward-pass memory wins are kept; a Pallas backward
kernel is future work. Attention dropout is not implemented (config enforces
``attention_dropout == 0`` for this impl).

Bias: only key-position masks — shape ``[B, 1, 1, Lk]`` additive, as produced
by ``ops.attention.make_attention_bias`` — are supported.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Measured sweet spot on TPU v5e (B=8, H=12, D=64, L=2048): (256, 512) runs
# 2.3x faster than (128, 128) — bigger K blocks amortize the per-matmul MXU
# ramp — and overtakes XLA's fused dot attention from L~2048. Shorter
# sequences clamp to L automatically.
DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 512

# Smallest blocks worth running as a Pallas grid. A whole-length single
# block is always fine (block == array dim); otherwise blocks below the TPU
# sublane/lane tile (8 query rows, 128 key columns) would lower poorly and
# a gcd-degenerate fit (e.g. prime L -> block 1) would build a pathological
# grid — those lengths take the XLA dot path instead (see flash_attention).
MIN_BLOCK_Q = 8
MIN_BLOCK_K = 128


def _fit(block: int, length: int) -> int:
    """Largest block <= the requested size that tiles ``length``: short
    sequences clamp to L, and lengths that aren't multiples of the
    default (e.g. 384 vs 512) snap to gcd."""
    if length <= block:
        return length
    import math

    return math.gcd(length, block)


def fits_blocks(lq: int, lk: int, block_q: int, block_k: int) -> bool:
    """Whether (lq, lk) tile into viable Pallas blocks for these requests.

    A block exactly as requested, or covering the whole length, is always
    viable (explicit small blocks are the caller's choice — tests use them
    under interpret mode); only a gcd fit that SHRANK the request below the
    TPU tile minimum is degenerate."""

    def ok(length: int, block: int, min_block: int) -> bool:
        fit = _fit(block, length)
        return fit == block or fit == length or fit >= min_block

    return ok(lq, block_q, MIN_BLOCK_Q) and ok(lk, block_k, MIN_BLOCK_K)


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, *, scale: float, block_k: int):
    """One query block vs. all key blocks, online softmax.

    Matmul inputs stay in the activation dtype (bf16 on TPU) with fp32 MXU
    accumulation — full MXU rate, and the same numerics as the dot path
    (ops/attention.py feeds bf16 into its einsums the same way). Softmax
    statistics and the accumulator are fp32.
    """
    q = q_ref[0, 0]  # [bq, D], activation dtype
    bq = q.shape[0]
    d = v_ref.shape[-1]
    lk = k_ref.shape[2]
    num_kb = lk // block_k

    def body(i, carry):
        acc, m, l = carry
        k_blk = k_ref[0, 0, pl.ds(i * block_k, block_k), :]
        v_blk = v_ref[0, 0, pl.ds(i * block_k, block_k), :]
        b_blk = bias_ref[0, 0, pl.ds(i * block_k, block_k)].astype(jnp.float32)
        s = (
            jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
            + b_blk[None, :]
        )  # [bq, bk] fp32
        m_new = jnp.maximum(m, s.max(axis=1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * alpha + p.sum(axis=1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, num_kb, body, (acc0, m0, l0))
    # -1e9 mask addends keep l > 0 even for fully masked rows (matches the
    # dot-attention path, which softmaxes the same finite scores).
    o_ref[0, 0] = (acc / l[:, None]).astype(o_ref.dtype)


def _key_bias(bias: jnp.ndarray | None, batch: int, lk: int) -> jnp.ndarray:
    """Returns [B, 1, Lk]: the middle singleton keeps the Pallas block's
    second-to-last dim equal to the array dim (the TPU lowering requires
    last-two block dims divisible by (8, 128) or equal to the array's)."""
    if bias is None:
        return jnp.zeros((batch, 1, lk), jnp.float32)
    if bias.ndim != 4 or bias.shape[1] != 1 or bias.shape[2] != 1:
        raise ValueError(
            f"flash_attention supports key-position bias [B,1,1,Lk] only, got {bias.shape}"
        )
    return bias[:, 0, :, :].astype(jnp.float32)


def _flash_forward(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: jnp.ndarray | None,
    *,
    block_q: int,
    block_k: int,
    interpret: bool,
) -> jnp.ndarray:
    b, h, lq, d = q.shape
    lk = k.shape[2]

    block_q = _fit(block_q, lq)
    block_k = _fit(block_k, lk)
    key_bias = _key_bias(bias, b, lk)
    scale = 1.0 / (d**0.5)
    kernel = functools.partial(_fwd_kernel, scale=scale, block_k=block_k)
    return pl.pallas_call(
        kernel,
        grid=(b, h, lq // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, lk, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, lk, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, lk), lambda bi, hi, qi: (bi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v, key_bias)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash(q, k, v, bias, block_q, block_k, interpret):
    return _flash_forward(
        q, k, v, bias, block_q=block_q, block_k=block_k, interpret=interpret
    )


def _flash_fwd(q, k, v, bias, block_q, block_k, interpret):
    out = _flash_forward(
        q, k, v, bias, block_q=block_q, block_k=block_k, interpret=interpret
    )
    return out, (q, k, v, bias, out)


def _flash_bwd(block_q, block_k, interpret, res, do):
    """Recompute-softmax backward (standard XLA ops, fp32)."""
    q, k, v, bias, out = res
    d = q.shape[-1]
    scale = 1.0 / (d**0.5)
    qf = q.astype(jnp.float32) * scale
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf, preferred_element_type=jnp.float32)
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    p = jax.nn.softmax(s, axis=-1)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, dof, preferred_element_type=jnp.float32)
    dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vf, preferred_element_type=jnp.float32)
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1)  # [B,H,Lq]
    ds = p * (dp - delta[..., None])
    dq = (
        jnp.einsum("bhqk,bhkd->bhqd", ds, kf, preferred_element_type=jnp.float32)
        * scale
    )
    dk = (
        jnp.einsum("bhqk,bhqd->bhkd", ds, qf, preferred_element_type=jnp.float32)
    )
    dbias = None
    if bias is not None:
        db = ds.sum(axis=(1, 2), keepdims=True)  # -> [B,1,1,Lk]
        dbias = db.astype(bias.dtype)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), dbias


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,  # [B, H, Lq, D]
    k: jnp.ndarray,  # [B, H, Lk, D]
    v: jnp.ndarray,  # [B, H, Lk, D]
    bias: jnp.ndarray | None = None,  # [B, 1, 1, Lk] additive key mask
    *,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Blocked flash attention; drop-in for ``dot_product_attention`` (minus
    attention dropout). ``interpret=None`` auto-selects interpreter mode off
    TPU so the same tests run on the CPU mesh.

    Lengths whose gcd with the requested blocks is degenerate (prime or odd
    L — block 1 would mean an Lq-step grid) fall back to the XLA dot path,
    which is faster than a shredded Pallas grid at any such length."""
    if not fits_blocks(q.shape[2], k.shape[2], block_q, block_k):
        from .attention import dot_product_attention

        return dot_product_attention(q, k, v, bias)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash(q, k, v, bias, block_q, block_k, interpret)
