"""Position-keyed (counter-based) dropout for sequence-sharded forwards.

Standard ``flax.linen.Dropout`` draws its mask from the rng stream in LOCAL
array order, so the same rng produces DIFFERENT masks depending on how the
sequence axis is sharded — a fedseq run at seq=2 would train a different
trajectory than the identical run at seq=1, and the reference's dropout-0.3
regularization (reference client1.py:57) could not be turned on under
sequence parallelism without breaking shard-count reproducibility.

Here the keep decision for every element is a pure hash of

    (64-bit seed, element coordinates ... with the position coordinate
     offset to its GLOBAL index)

— the same construction as the Pallas flash-attention kernels' dropout
(ops/flash_attention.py::_keep_mask), in plain XLA ops so it runs inside
``shard_map``/``vmap`` anywhere. A shard at offset k hashes positions
[k, k+L_local) and therefore reproduces exactly the mask slice the
unsharded run computes for those positions: masks are invariant to the
seq-axis shard count by construction.

Distribution note: same Bernoulli(1-rate) marginals as ``nn.Dropout``,
different bits (hash stream vs threefry stream) — the same contract the
flash kernels already set.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# One odd mixing constant per coordinate axis (murmur/xxhash-style).
_AXIS_CONSTS = (0x9E3779B1, 0x85EBCA77, 0xC2B2AE3D, 0x27D4EB2F, 0x165667B1)


def hash_keep_mask(
    seed: jax.Array,  # (2,) uint32 — 64-bit seed
    shape: tuple[int, ...],
    rate: float,
    *,
    offsets: dict[int, jax.Array] | None = None,
) -> jnp.ndarray:
    """fp32 0/1 keep mask of ``shape``: element (i0, i1, ...) keeps iff
    murmur-finalized hash of (seed, i0+off0, i1+off1, ...) clears the rate
    threshold. ``offsets`` maps axis -> (traced) global offset of this
    shard along that axis."""
    if len(shape) > len(_AXIS_CONSTS):
        raise ValueError(f"hash_keep_mask supports rank <= {len(_AXIS_CONSTS)}")
    offsets = offsets or {}
    x = jnp.zeros(shape, jnp.uint32)
    for axis in range(len(shape)):
        idx = jax.lax.broadcasted_iota(jnp.uint32, shape, axis)
        off = offsets.get(axis)
        if off is not None:
            idx = idx + jnp.asarray(off).astype(jnp.uint32)
        # Mix each coordinate with its own odd constant; xor keeps the
        # combination bijective per-axis before the finalizer avalanches.
        x = x ^ (idx * jnp.uint32(_AXIS_CONSTS[axis]))
    s0 = jnp.asarray(seed[0]).astype(jnp.uint32)
    s1 = jnp.asarray(seed[1]).astype(jnp.uint32)
    x = x ^ s0
    x = x + s1 * jnp.uint32(0x632BE59B)
    # murmur3 finalizer (identical to ops/flash_attention.py::_keep_mask).
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    thresh = jnp.uint32(min(2**32 - 1, int(round(rate * 4294967296.0))))
    return (x >= thresh).astype(jnp.float32)


def hash_dropout(
    x: jnp.ndarray,
    rate: float,
    rng: jax.Array,
    *,
    offsets: dict[int, jax.Array] | None = None,
    deterministic: bool = False,
) -> jnp.ndarray:
    """Inverted dropout with a coordinate-keyed hash mask.

    ``offsets`` maps each SHARDED axis of ``x`` to this shard's global
    start index along it — pass ``jax.lax.axis_index(axis_name) *
    x.shape[axis]`` inside ``shard_map`` for the sequence axis AND the
    batch axis (rows on different data shards must not reuse one mask).
    The rng key must be identical on every shard (it is: flax
    ``make_rng`` folds only the module path, which does not vary over
    shards)."""
    if deterministic or rate == 0.0:
        return x
    seed = jax.random.bits(rng, (2,), jnp.uint32)
    keep = hash_keep_mask(seed, x.shape, rate, offsets=offsets)
    return (x * keep.astype(x.dtype)) / jnp.asarray(1.0 - rate, x.dtype)
