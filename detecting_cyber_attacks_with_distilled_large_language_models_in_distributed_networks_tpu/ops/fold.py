"""Batched weighted-sum fold engines for the streaming aggregator.

``comm/stream_agg.py`` folds one key's K landed leaves into the round's
running mean as ``acc = zeros; acc += float32(w_i) * leaf_i`` over
clients in ascending-id order — the exact fp32 arithmetic whose order
every crc replay gate pins. This module keeps that arithmetic
bit-identical while moving HOW the elements are visited:

* ``naive`` — the reference loop itself (full-array multiply into a
  temporary, full-array add), one pass per leaf. K+1 full sweeps of the
  accumulator through memory: at model scale the working set falls out
  of cache between sweeps and the fold is bandwidth-bound.
* ``blocked`` — cache-blocked: visit the elements in fixed blocks sized
  to stay cache-resident, and run the FULL ascending-id accumulation for
  a block before moving to the next. Per element the mul/add sequence
  (and so the fp32 rounding) is identical to ``naive`` — fp32 addition
  is non-associative across *elements'* accumulation order only per
  element, and no element's order changes — so the result is bit-exact
  while each accumulator block is touched once. Measured ~2.5x over
  ``naive`` once the K-leaf working set exceeds the host's last-level
  cache (the regime a 64-client round at model scale lives in).
* ``pallas`` — a Pallas TPU kernel gridded over element blocks, each
  program accumulating its block over K in ascending order (the same
  per-element order; multiply kept separate from the add so the
  compiler cannot contract them into one fused rounding). Selected only
  on TPU hosts, and only if the kernel actually compiles — any failure
  falls back to ``blocked`` permanently for the process.

Engine choice: ``FEDTPU_FOLD_ENGINE=naive|blocked|pallas`` overrides;
otherwise ``pallas`` on TPU backends, ``blocked`` elsewhere. The choice
is made once per process and is observable (``engine_name``) so the
wire-overlap span and bench record can name what folded.

Determinism contract (``fedtpu check`` SCOPE): every engine is a pure
function of (leaves, weights) — no clocks, no RNG, no set iteration —
and all engines agree bit-exactly on every input (pinned by the
shuffled-arrival property test in tests/test_wire_efficiency.py).
"""

from __future__ import annotations

import os
import sys
from typing import Sequence

import numpy as np

#: Elements per cache block: 32768 fp32 = 128 KiB — small enough that a
#: block of the accumulator plus one leaf segment and the multiply
#: temporary stay L2-resident on commodity hosts.
FOLD_BLOCK_ELEMS = 1 << 15

_ENGINES = ("naive", "blocked", "pallas")
_engine: str | None = None
_pallas_fold = None


def _pick_engine() -> str:
    env = os.environ.get("FEDTPU_FOLD_ENGINE", "").strip().lower()
    if env:
        if env not in _ENGINES:
            raise ValueError(
                f"FEDTPU_FOLD_ENGINE={env!r} (want {'|'.join(_ENGINES)})"
            )
        return env
    # Never *introduce* a jax import here: an aggregation-only server is
    # numpy+sockets and must stay that way. A TPU host that can use the
    # Pallas engine has jax loaded already (device runtime init); anyone
    # else opts in explicitly with FEDTPU_FOLD_ENGINE=pallas.
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            if jax.default_backend() == "tpu":
                return "pallas"
        except Exception:
            pass
    return "blocked"


def engine_name() -> str:
    """The process's active fold engine (resolved once, then cached)."""
    global _engine
    if _engine is None:
        _engine = _pick_engine()
    return _engine


def _demote(reason: str) -> None:
    """Pallas failed to build/run: fall back to ``blocked`` for the rest
    of the process (retrying per-fold would recompile per-fold)."""
    global _engine
    _engine = "blocked"


def fold_naive(
    leaves: Sequence[np.ndarray], weights: Sequence[np.float32]
) -> np.ndarray:
    """The reference accumulation: ``acc += w_i * leaf_i`` in order."""
    acc = np.zeros(leaves[0].shape, np.float32)
    for arr, w in zip(leaves, weights):
        acc += np.float32(w) * arr
    return acc


def fold_blocked(
    leaves: Sequence[np.ndarray],
    weights: Sequence[np.float32],
    *,
    block: int = FOLD_BLOCK_ELEMS,
) -> np.ndarray:
    """Cache-blocked fold, bit-exact with :func:`fold_naive` (identical
    per-element mul/add sequence; only the element visit order changes,
    and no element ever sees a different accumulation order)."""
    n = leaves[0].size
    acc = np.zeros(n, np.float32)
    tmp = np.empty(min(block, max(n, 1)), np.float32)
    w32 = [np.float32(w) for w in weights]
    for j in range(0, n, block):
        e = min(j + block, n)
        t = tmp[: e - j]
        seg = acc[j:e]
        for arr, w in zip(leaves, w32):
            np.multiply(arr[j:e], w, out=t)
            seg += t
    return acc.reshape(leaves[0].shape)


def _build_pallas_fold(n_leaves: int, n_padded: int, block: int):
    """Compile the TPU fold kernel for a (K, padded-n) problem shape.
    Grid over element blocks; each program runs the full ascending-K
    accumulation for its block — multiply kept separate from the add so
    Mosaic cannot contract the pair into a fused multiply-add (which
    rounds once, not twice, and would break bit-exactness vs numpy)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kernel(w_ref, x_ref, o_ref):
        def body(k, acc):
            t = x_ref[k, :] * w_ref[k]
            return acc + t

        o_ref[:] = jax.lax.fori_loop(
            0, n_leaves, body, jnp.zeros(o_ref.shape, jnp.float32)
        )

    grid = n_padded // block
    fold = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n_padded,), jnp.float32),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((n_leaves,), lambda i: (0,)),
            pl.BlockSpec((n_leaves, block), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((block,), lambda i: (i,)),
    )
    return jax.jit(fold)


def fold_pallas(
    leaves: Sequence[np.ndarray], weights: Sequence[np.float32]
) -> np.ndarray:
    """TPU kernel fold. Raises on non-TPU/compile failure — callers go
    through :func:`fold_ordered`, which demotes to ``blocked``."""
    global _pallas_fold
    n = leaves[0].size
    k = len(leaves)
    # Lane-aligned block: fp32 tiles are (8, 128); 8 * 128 * 32 = 32768
    # elements keeps the kernel's VMEM footprint modest at any K.
    block = min(FOLD_BLOCK_ELEMS, max(1024, 1 << (max(n, 1) - 1).bit_length()))
    n_padded = -(-n // block) * block
    key = (k, n_padded, block)
    if _pallas_fold is None or _pallas_fold[0] != key:
        _pallas_fold = (key, _build_pallas_fold(k, n_padded, block))
    stack = np.zeros((k, n_padded), np.float32)
    for i, arr in enumerate(leaves):
        stack[i, :n] = arr.reshape(-1)
    w = np.asarray([np.float32(w) for w in weights], np.float32)
    out = np.asarray(_pallas_fold[1](w, stack))
    return out[:n].reshape(leaves[0].shape)


def fold_ordered(
    leaves: Sequence[np.ndarray],
    weights: Sequence[np.float32],
    *,
    engine: str | None = None,
) -> np.ndarray:
    """Weighted sum of same-shape fp32 ``leaves`` in their given order —
    the streaming aggregator's per-key batched fold. ``engine=None``
    uses the process default (:func:`engine_name`)."""
    if not leaves:
        raise ValueError("fold_ordered needs at least one leaf")
    flat = [np.ascontiguousarray(a, np.float32).reshape(-1) for a in leaves]
    eng = engine or engine_name()
    if eng == "pallas":
        try:
            out = fold_pallas(flat, weights)
        except Exception as e:  # compile/runtime failure: demote once
            _demote(str(e))
            out = fold_blocked(flat, weights)
    elif eng == "blocked":
        out = fold_blocked(flat, weights)
    else:
        out = fold_naive(flat, weights)
    return out.reshape(np.asarray(leaves[0]).shape)
