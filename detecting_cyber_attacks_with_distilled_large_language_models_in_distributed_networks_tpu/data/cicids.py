"""CICIDS2017 loading, imputation, per-client partitioning, splits.

Reference semantics reproduced exactly (they determine accuracy parity):

* CSV load; ``±inf -> NaN``; NaN -> column mean (numeric columns only)
  — reference client1.py:86-88.
* Per-client fraction sample with a per-client seed: client 1 uses
  ``random_state=42`` (reference client1.py:89), client 2 uses 43
  (reference client2.py:84). Here the seed is derived: ``seed_base + client_id``.
* 60/20/20 train/val/test via two chained shuffled splits with the same seed
  — reference client1.py:365-366.
* Label map ``'DDoS' -> 1 else 0`` — reference client1.py:91.

Beyond the reference: disjoint, Dirichlet label-skew, and quantity-skew
non-IID partitioners (BASELINE.json config 3; data/partition.py),
parameterized over N clients instead of one copy-pasted script per
client.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import pandas as pd

from ..config import DataConfig
from .datasets import Corpus, get_dataset
from .partition import partition_indices  # noqa: F401  (re-export)
from .partition import log_manifest, partition_manifest, save_manifest
from .textualize import labels_from_dataframe  # noqa: F401  (re-export)


def _spec_texts(df: pd.DataFrame, cfg: DataConfig) -> list[str]:
    return get_dataset(cfg.dataset).render_texts(df)


def _spec_labels(df: pd.DataFrame, cfg: DataConfig) -> np.ndarray:
    """Binary labels under the active dataset spec; for CICIDS2017-style
    positive-match labels the config's label_column/positive_label knobs
    still apply (reference client1.py:91 semantics)."""
    spec = get_dataset(cfg.dataset)
    if spec.label_kind == "positive":
        return spec.binary_labels(
            df, label_column=cfg.label_column, positive_value=cfg.positive_label
        )
    return spec.binary_labels(df)


def load_flow_csv(path: str) -> pd.DataFrame:
    """Load a CICIDS2017-style CSV and impute non-finite values.

    Column names are whitespace-stripped (real CICIDS2017 exports carry leading
    spaces on some headers; the reference's stub is clean for the 10 rendered
    columns so this is a superset of its behavior).
    """
    df = pd.read_csv(path, skipinitialspace=True)
    df.columns = [c.strip() for c in df.columns]
    df = df.replace([np.inf, -np.inf], np.nan)
    df = df.fillna(df.mean(numeric_only=True))
    return df


def sample_client_frame(df: pd.DataFrame, frac: float, seed: int) -> pd.DataFrame:
    """Reference-style per-client sample: ``df.sample(frac, random_state=seed)``
    (reference client1.py:89). Independent samples per client — overlap between
    clients is possible, exactly as in the reference."""
    return df.sample(frac=frac, random_state=seed)


def _two_way_split(
    n: int, test_size: float, seed: int
) -> tuple[np.ndarray, np.ndarray]:
    """Shuffled split matching sklearn.model_selection.train_test_split
    semantics (ceil on the test side), which the reference uses at
    client1.py:365-366."""
    n_test = int(np.ceil(n * test_size))
    n_train = int(np.floor(n * (1.0 - test_size)))
    rng = np.random.RandomState(seed)
    perm = rng.permutation(n)
    return perm[n_test : n_test + n_train], perm[:n_test]


def train_val_test_split(
    n: int, seed: int, val_fraction: float = 0.2, test_fraction: float = 0.2
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """60/20/20 via two chained splits, same seed for both — reference
    client1.py:365-366 (``test_size=0.4`` then ``test_size=0.5``)."""
    holdout = val_fraction + test_fraction
    train_idx, temp_idx = _two_way_split(n, holdout, seed)
    val_rel, test_rel = _two_way_split(len(temp_idx), test_fraction / holdout, seed)
    return train_idx, temp_idx[val_rel], temp_idx[test_rel]


@dataclass
class SplitArrays:
    texts: list[str]
    labels: np.ndarray

    def __len__(self) -> int:
        return len(self.texts)


@dataclass
class ClientSplits:
    client_id: int
    train: SplitArrays
    val: SplitArrays
    test: SplitArrays

    @property
    def n_train(self) -> int:
        return len(self.train)


def _all_client_frames(
    df: pd.DataFrame, num_clients: int, cfg: DataConfig
) -> list[pd.DataFrame]:
    """Partition an (already imputed) frame into per-client frames.

    The index-based schemes compute the full partition once (O(n), not
    O(n*num_clients)).
    """
    if cfg.partition == "sample":
        return [
            sample_client_frame(df, cfg.data_fraction, cfg.client_seed(cid))
            for cid in range(num_clients)
        ]
    labels = _spec_labels(df, cfg)
    parts = partition_indices(labels, num_clients, cfg)
    return [df.iloc[idx] for idx in parts]


def load_client_frame(
    df: pd.DataFrame, client_id: int, num_clients: int, cfg: DataConfig
) -> pd.DataFrame:
    """One client's rows. For index-based schemes prefer the batch API
    (:func:`make_all_client_splits`) when loading a whole fleet."""
    if cfg.partition == "sample":
        return sample_client_frame(df, cfg.data_fraction, cfg.client_seed(client_id))
    return _all_client_frames(df, num_clients, cfg)[client_id]


def _splits_from_arrays(
    texts: list[str], labels: np.ndarray, client_id: int, cfg: DataConfig
) -> ClientSplits:
    tr, va, te = train_val_test_split(
        len(texts), cfg.client_seed(client_id), cfg.val_fraction, cfg.test_fraction
    )

    def _take(idx: np.ndarray) -> SplitArrays:
        return SplitArrays([texts[i] for i in idx], labels[idx])

    return ClientSplits(client_id, _take(tr), _take(va), _take(te))


def _splits_from_frame(
    part: pd.DataFrame, client_id: int, cfg: DataConfig
) -> ClientSplits:
    return _splits_from_arrays(
        _spec_texts(part, cfg), _spec_labels(part, cfg), client_id, cfg
    )


def make_client_splits(
    df: pd.DataFrame, client_id: int, num_clients: int, cfg: DataConfig
) -> ClientSplits:
    """Full host-side path for one client: partition -> textualize -> split."""
    part = load_client_frame(df, client_id, num_clients, cfg)
    return _splits_from_frame(part, client_id, cfg)


def make_all_client_splits(
    df: pd.DataFrame,
    num_clients: int,
    cfg: DataConfig,
    *,
    manifest_path: str | None = None,
) -> list[ClientSplits]:
    """All clients in one pass (the partition is computed once). The
    per-client label-histogram manifest is logged, and written as JSON
    when ``manifest_path`` is given (data/partition.py)."""
    frames = _all_client_frames(df, num_clients, cfg)
    # One label pass per frame, shared by the manifest AND the split
    # builder (the label mapping is a full-frame pandas pass per client).
    labels = [_spec_labels(p, cfg) for p in frames]
    manifest = partition_manifest(labels, cfg=cfg, total_rows=len(df))
    log_manifest(manifest)
    if manifest_path:
        save_manifest(manifest, manifest_path)
    return [
        _splits_from_arrays(_spec_texts(p, cfg), lab, cid, cfg)
        for cid, (p, lab) in enumerate(zip(frames, labels))
    ]


def make_all_client_splits_from_corpus(
    corpus: Corpus,
    num_clients: int,
    cfg: DataConfig,
    *,
    manifest_path: str | None = None,
) -> list[ClientSplits]:
    """Per-client splits over a schema-erased (possibly mixed-dataset) corpus.

    Same partition semantics as the frame path: ``sample`` draws an
    independent ``data_fraction`` subset per client seed (the reference's
    ``df.sample(frac, random_state)``, client1.py:89, on row indices);
    ``disjoint``/``dirichlet`` reuse :func:`partition_indices` on the binary
    labels. Mixed corpora are shuffled together, so a client's shard can span
    source datasets — the point of BASELINE.json config 5.
    """
    n = len(corpus)
    if cfg.partition == "sample":
        per_client = max(1, int(round(n * cfg.data_fraction)))
        parts = [
            np.random.RandomState(cfg.client_seed(cid)).permutation(n)[:per_client]
            for cid in range(num_clients)
        ]
    else:
        parts = partition_indices(corpus.labels, num_clients, cfg)
    manifest = partition_manifest(
        [corpus.labels[idx] for idx in parts], cfg=cfg, total_rows=n
    )
    log_manifest(manifest)
    if manifest_path:
        save_manifest(manifest, manifest_path)
    return [
        _splits_from_arrays(
            [corpus.texts[i] for i in idx], corpus.labels[idx], cid, cfg
        )
        for cid, idx in enumerate(parts)
    ]
