"""WordPiece tokenizer (BERT-compatible), pure Python + numpy.

The reference tokenizes with HF ``DistilBertTokenizer`` loaded from a local
``./distilbert-base-uncased`` directory that must pre-exist (reference
client1.py:357,360-364), with ``add_special_tokens=True, max_length=128,
padding='max_length', truncation=True`` per sample inside a torch ``Dataset``
(reference client1.py:36-50) — i.e. tokenization re-runs every epoch on the
host. Here tokenization is a one-shot offline batch encode into static-shape
``[N, max_len]`` int32 arrays that feed the TPU directly.

Algorithm parity: BasicTokenizer (clean, lowercase, accent-strip, punctuation
split) + greedy longest-match WordPiece with ``##`` continuations — the exact
scheme of BERT's reference implementation, verified in tests against
``transformers.BertTokenizer`` (which is what DistilBertTokenizer aliases).

Because this image has no pretrained vocab (zero egress), the default vocab is
*domain-complete*: every sentence the flow-template (textualize.py) can emit
tokenizes with zero ``[UNK]``s — template words as whole tokens, plus full
single-character + continuation coverage of ``[a-z0-9]`` and ASCII punctuation.
A real ``vocab.txt`` (e.g. bert-base-uncased's 30522 entries) drops in via
``WordPieceTokenizer.from_vocab_file`` for checkpoint parity.
"""

from __future__ import annotations

import string
import unicodedata
from collections import Counter
from typing import Iterable, Mapping, Sequence

import numpy as np

PAD, UNK, CLS, SEP, MASK = "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"
SPECIAL_TOKENS = (PAD, UNK, CLS, SEP, MASK)

#: Whole words appearing in the flow-text template (textualize.py), lowercased.
TEMPLATE_WORDS: tuple[str, ...] = (
    "destination", "port", "is", "flow", "duration", "microseconds",
    "total", "forward", "packets", "are", "backward", "length", "of",
    "bytes", "maximum", "packet", "minimum", "per", "second", "nan", "inf",
)

#: UNSW-NB15 template words (datasets.py UNSW_TEMPLATE) plus the categorical
#: values its proto/service columns commonly take. Appended AFTER the
#: char/punct block in build_domain_vocab so every pre-existing token keeps
#: its id (already-tokenized data stays valid). The vocab still GROWS, so a
#: model checkpoint pinned to the old vocab_size has a smaller embedding
#: table — maybe_warm_start degrades to a fresh start on that mismatch.
EXTRA_TEMPLATE_WORDS: tuple[str, ...] = (
    "protocol", "service", "seconds", "source", "to", "rate", "load", "bits",
    "tcp", "udp", "arp", "icmp", "http", "dns", "smtp", "ftp", "ssh", "normal",
)


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) or (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_control(ch: str) -> bool:
    if ch in ("\t", "\n", "\r"):
        return False
    return unicodedata.category(ch).startswith("C")


def _is_whitespace(ch: str) -> bool:
    if ch in (" ", "\t", "\n", "\r"):
        return True
    return unicodedata.category(ch) == "Zs"


def basic_tokenize(text: str, lowercase: bool = True) -> list[str]:
    """BERT BasicTokenizer: clean, whitespace-split, lowercase + accent-strip,
    split punctuation into standalone tokens."""
    cleaned = []
    for ch in text:
        cp = ord(ch)
        if cp == 0 or cp == 0xFFFD or _is_control(ch):
            continue
        cleaned.append(" " if _is_whitespace(ch) else ch)
    out: list[str] = []
    for word in "".join(cleaned).split():
        if lowercase:
            word = word.lower()
            word = "".join(
                c for c in unicodedata.normalize("NFD", word)
                if unicodedata.category(c) != "Mn"
            )
        cur: list[str] = []
        for ch in word:
            if _is_punctuation(ch):
                if cur:
                    out.append("".join(cur))
                    cur = []
                out.append(ch)
            else:
                cur.append(ch)
        if cur:
            out.append("".join(cur))
    return out


def build_domain_vocab(
    corpus: Iterable[str] | None = None,
    max_corpus_words: int = 10000,
    min_freq: int = 1,
) -> list[str]:
    """Vocab that fully covers the flow-text domain; optionally extended with
    frequent whole words from a corpus (most-frequent first, deterministic)."""
    vocab: list[str] = list(SPECIAL_TOKENS)
    seen = set(vocab)

    def _add(tok: str) -> None:
        if tok and tok not in seen:
            vocab.append(tok)
            seen.add(tok)

    for w in TEMPLATE_WORDS:
        _add(w)
    base_chars = string.ascii_lowercase + string.digits
    for c in base_chars:
        _add(c)
        _add("##" + c)
    for c in string.punctuation:
        _add(c)
    # New whole-word entries go after the stable id range (see
    # EXTRA_TEMPLATE_WORDS): ids 0..129 are frozen for back-compat.
    for w in EXTRA_TEMPLATE_WORDS:
        _add(w)
    if corpus is not None:
        counts: Counter[str] = Counter()
        for text in corpus:
            for tok in basic_tokenize(text):
                counts[tok] += 1
        for tok, freq in sorted(counts.items(), key=lambda kv: (-kv[1], kv[0])):
            if freq < min_freq or len(seen) - len(SPECIAL_TOKENS) >= max_corpus_words:
                break
            _add(tok)
    return vocab


def build_reference_scale_vocab(size: int = 30522) -> list[str]:
    """A deterministic vocab at the reference's REAL scale — 30522 entries,
    the vocab_size of its required ``./distilbert-base-uncased``
    (client1.py:56,357 via HF) — for end-to-end exercises of the full
    embedding table and WordPiece path without network access.

    Layout: the domain vocab first (template words, chars, ##-pieces —
    flow texts tokenize with zero [UNK]s), then whole-number tokens
    0..9999 and their ##-continuations (realistic multi-piece numerals),
    then ``[unusedN]`` filler up to exactly ``size``."""
    vocab = build_domain_vocab()
    seen = set(vocab)

    def _add(tok: str) -> None:
        if tok not in seen and len(vocab) < size:
            vocab.append(tok)
            seen.add(tok)

    for n in range(10_000):
        _add(str(n))
    for n in range(10_000):
        _add(f"##{n}")
    i = 0
    while len(vocab) < size:
        _add(f"[unused{i}]")
        i += 1
    if len(vocab) != size:
        raise ValueError(f"vocab overflow: base entries exceed size={size}")
    return vocab


class WordPieceTokenizer:
    """Greedy longest-match WordPiece over a BasicTokenizer pre-split."""

    def __init__(
        self,
        vocab: Sequence[str] | Mapping[str, int],
        lowercase: bool = True,
        max_input_chars_per_word: int = 100,
    ):
        if isinstance(vocab, Mapping):
            self.vocab: dict[str, int] = dict(vocab)
        else:
            self.vocab = {tok: i for i, tok in enumerate(vocab)}
        if len(self.vocab) < len(SPECIAL_TOKENS):
            raise ValueError("vocab too small")
        for tok in SPECIAL_TOKENS:
            if tok not in self.vocab:
                raise ValueError(f"vocab missing special token {tok}")
        self.inv_vocab = {i: t for t, i in self.vocab.items()}
        self.lowercase = lowercase
        self.max_input_chars_per_word = max_input_chars_per_word
        self.pad_id = self.vocab[PAD]
        self.unk_id = self.vocab[UNK]
        self.cls_id = self.vocab[CLS]
        self.sep_id = self.vocab[SEP]
        self._word_cache: dict[str, list[int]] = {}
        self._native = None  # lazily created by batch_encode
        self._native_tried = False

    def __len__(self) -> int:
        return len(self.vocab)

    @classmethod
    def from_vocab_file(cls, path: str, **kw) -> "WordPieceTokenizer":
        with open(path, encoding="utf-8") as f:
            tokens = [line.rstrip("\n") for line in f if line.rstrip("\n")]
        return cls(tokens, **kw)

    def save_vocab(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            for tok, _ in sorted(self.vocab.items(), key=lambda kv: kv[1]):
                f.write(tok + "\n")

    def _wordpiece(self, word: str) -> list[int]:
        # Flow text is dominated by unique numeric strings — caching those
        # would grow without bound at near-zero hit rate. Cache only
        # alphabetic words (template vocabulary), which repeat constantly;
        # the size cap bounds insertions only — lookups always hit.
        cacheable = word.isalpha()
        cached = self._word_cache.get(word) if cacheable else None
        if cached is not None:
            return cached
        if len(word) > self.max_input_chars_per_word:
            ids = [self.unk_id]
        else:
            ids = []
            start = 0
            n = len(word)
            while start < n:
                end = n
                piece_id = None
                while start < end:
                    sub = word[start:end]
                    if start > 0:
                        sub = "##" + sub
                    pid = self.vocab.get(sub)
                    if pid is not None:
                        piece_id = pid
                        break
                    end -= 1
                if piece_id is None:
                    ids = [self.unk_id]
                    break
                ids.append(piece_id)
                start = end
        if cacheable and len(self._word_cache) < 65536:
            self._word_cache[word] = ids
        return ids

    def tokenize(self, text: str) -> list[str]:
        return [
            self.inv_vocab[i]
            for w in basic_tokenize(text, self.lowercase)
            for i in self._wordpiece(w)
        ]

    def encode(self, text: str, max_len: int | None = None) -> list[int]:
        """``[CLS] pieces... [SEP]`` truncated to ``max_len`` (specials kept),
        matching HF ``add_special_tokens=True, truncation=True``."""
        ids = [
            i for w in basic_tokenize(text, self.lowercase) for i in self._wordpiece(w)
        ]
        if max_len is not None:
            ids = ids[: max_len - 2]
        return [self.cls_id, *ids, self.sep_id]

    def _native_encoder(self):
        """Lazily bind this vocab into the native batch encoder
        (data/native_tokenizer.py). Only when the vocab is dense (ids
        0..n-1), newline-free, and the word-length cap is the native
        default — otherwise the Python path is authoritative."""
        if self._native_tried:
            return self._native
        self._native_tried = True
        if self.max_input_chars_per_word != 100:
            return None
        tokens: list[str | None] = [None] * len(self.vocab)
        for tok, i in self.vocab.items():
            # Empty tokens would vanish from the '\n'-joined native vocab
            # blob and shift every later id — Python path only for those.
            if (
                not tok
                or "\n" in tok
                or not (0 <= i < len(tokens))
                or tokens[i] is not None
            ):
                return None
            tokens[i] = tok
        if any(t is None for t in tokens):
            return None
        from .native_tokenizer import NativeWordPiece

        self._native = NativeWordPiece.create(tokens)  # None without toolchain
        return self._native

    def batch_encode(
        self, texts: Sequence[str], max_len: int = 128
    ) -> dict[str, np.ndarray]:
        """Static-shape ``[N, max_len]`` int32 ``input_ids`` + ``attention_mask``
        (the TPU feed format; equivalent to HF ``padding='max_length'``).

        Pure-ASCII batches take the native C++ encoder when available
        (bit-identical output, ~an order of magnitude faster); anything else
        — non-ASCII text, exotic vocabs, no toolchain — runs the Python
        implementation below.
        """
        native = self._native_encoder()
        if native is not None:
            out = native.encode_batch(texts, max_len, lowercase=self.lowercase)
            if out is not None:
                return out
        n = len(texts)
        input_ids = np.full((n, max_len), self.pad_id, dtype=np.int32)
        attention_mask = np.zeros((n, max_len), dtype=np.int32)
        for r, text in enumerate(texts):
            ids = self.encode(text, max_len)
            input_ids[r, : len(ids)] = ids
            attention_mask[r, : len(ids)] = 1
        return {"input_ids": input_ids, "attention_mask": attention_mask}


def default_tokenizer() -> WordPieceTokenizer:
    return WordPieceTokenizer(build_domain_vocab())
