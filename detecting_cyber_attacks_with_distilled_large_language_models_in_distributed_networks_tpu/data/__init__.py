from .textualize import FLOW_TEXT_COLUMNS, flow_to_text, texts_from_dataframe  # noqa: F401
from .cicids import (  # noqa: F401
    ClientSplits,
    SplitArrays,
    load_client_frame,
    load_flow_csv,
    make_all_client_splits,
    make_client_splits,
    partition_indices,
    train_val_test_split,
)
from .synthetic import make_synthetic_flows, write_synthetic_csv  # noqa: F401
from .tokenizer import (  # noqa: F401
    WordPieceTokenizer,
    basic_tokenize,
    build_domain_vocab,
    default_tokenizer,
)
from .pipeline import (  # noqa: F401
    TokenizedClient,
    TokenizedSplit,
    batch_iterator,
    num_batches,
    pad_split_to_batch,
    stack_clients,
    tokenize_client,
    tokenize_split,
)
