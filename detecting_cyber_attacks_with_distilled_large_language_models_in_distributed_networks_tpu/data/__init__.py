from .textualize import (  # noqa: F401
    CICIDS_TEMPLATE,
    FLOW_TEXT_COLUMNS,
    flow_to_text,
    render_template,
    texts_from_dataframe,
)
from .datasets import (  # noqa: F401
    DATASETS,
    Corpus,
    DatasetSpec,
    UNSW_TEMPLATE,
    concat_corpora,
    corpus_from_frame,
    detect_dataset,
    get_dataset,
    load_mixed_corpus,
    parse_source_arg,
)
from .cicids import (  # noqa: F401
    ClientSplits,
    SplitArrays,
    load_client_frame,
    load_flow_csv,
    make_all_client_splits,
    make_all_client_splits_from_corpus,
    make_client_splits,
    train_val_test_split,
)
from .partition import (  # noqa: F401
    PARTITION_SCHEMES,
    dirichlet_label_indices,
    log_manifest,
    partition_indices,
    partition_manifest,
    quantity_skew_indices,
    save_manifest,
)
from .synthetic import (  # noqa: F401
    make_synthetic,
    make_synthetic_ddos2019,
    make_synthetic_flows,
    make_synthetic_unsw,
    write_synthetic_csv,
)
from .tokenizer import (  # noqa: F401
    WordPieceTokenizer,
    basic_tokenize,
    build_domain_vocab,
    default_tokenizer,
)
from .streaming import (  # noqa: F401
    stream_client_tokens,
    stream_client_tokens_for,
)
from .pipeline import (  # noqa: F401
    TokenizedClient,
    TokenizedSplit,
    batch_iterator,
    num_batches,
    pad_split_to_batch,
    StackedClients,
    stack_clients,
    stack_clients_ragged,
    tokenize_client,
    tokenize_split,
)
