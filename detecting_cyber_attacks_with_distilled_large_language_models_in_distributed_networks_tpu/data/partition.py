"""Client data partitioners: one source dataset -> N client shards.

The reference's only notion of partitioning is an independent
``df.sample(frac, random_state=seed)`` per copy-pasted client script
(reference client1.py:89, client2.py:84) — IID by construction, overlap
between clients possible. The index-based schemes here are the
"federated in the wild" knobs that IID sampling never exercises:

* ``disjoint``  — equal disjoint shards of one global permutation (IID,
                  no overlap).
* ``dirichlet`` — classic label-skew non-IID (Hsu et al.): for each
                  class, split its rows among clients by
                  Dirichlet(alpha) proportions. alpha -> 0 pushes every
                  client toward a near-single-class shard — the
                  non-IID + unbalanced setting of arXiv:2509.17836.
* ``quantity``  — quantity skew: disjoint IID-content shards whose
                  SIZES are drawn from Dirichlet(alpha). alpha -> 0
                  concentrates most rows on few clients (the
                  heterogeneous/lazy-client regime of TurboSVM-FL,
                  arXiv:2401.12012) while each shard's label mix stays
                  representative.

Every scheme is seeded from ``DataConfig.seed_base`` and shared by BOTH
deployment tiers — the mesh tier (cli/federated.py) and the TCP tier
(cli/comm.py) shard through the same :func:`partition_indices`, so
client i holds the identical row set no matter which tier trains it
(pinned by tests/test_partition.py). Each partition also yields a
MANIFEST of per-client label histograms (logged, and written next to
the run outputs) so a non-IID run records exactly what every client
saw.
"""

from __future__ import annotations

import json
import os
from typing import Sequence

import numpy as np

from ..config import DataConfig
from ..utils.logging import get_logger

log = get_logger()

#: Registered partition schemes (``sample`` is the reference's
#: per-client fraction sample, implemented in data/cicids.py; the rest
#: are index-based and dispatch through :func:`partition_indices`).
PARTITION_SCHEMES = ("sample", "disjoint", "dirichlet", "quantity")

#: Default filename the CLI writes the manifest under (in output_dir).
MANIFEST_FILENAME = "partition_manifest.json"


def dirichlet_label_indices(
    labels: np.ndarray,
    num_clients: int,
    *,
    alpha: float,
    data_fraction: float,
    rng: np.random.Generator,
) -> list[np.ndarray]:
    """Label-skew partition: per class, shuffle its rows and split them
    among clients by Dirichlet(alpha) proportions. ``data_fraction`` is
    per-dataset (each client targets ``frac * n`` rows in expectation;
    the class cap is ``frac * num_clients`` of each class's rows)."""
    out: list[list[np.ndarray]] = [[] for _ in range(num_clients)]
    for cls in np.unique(labels):
        idx = np.flatnonzero(labels == cls)
        rng.shuffle(idx)
        idx = idx[: max(1, int(len(idx) * data_fraction * num_clients))]
        props = rng.dirichlet([alpha] * num_clients)
        cuts = (np.cumsum(props)[:-1] * len(idx)).astype(int)
        for cid, chunk in enumerate(np.split(idx, cuts)):
            out[cid].append(chunk)
    return [
        np.concatenate(chunks) if chunks else np.array([], int)
        for chunks in out
    ]


def quantity_skew_indices(
    n: int,
    num_clients: int,
    *,
    alpha: float,
    data_fraction: float,
    rng: np.random.Generator,
) -> list[np.ndarray]:
    """Quantity-skew partition: one global permutation cut into disjoint
    shards whose sizes follow Dirichlet(alpha) — IID content, unbalanced
    counts. Every client is guaranteed at least one row (a zero-row
    client would crash its local loader, and a Dirichlet draw lands on
    exact zero with probability > 0 only through float truncation
    anyway)."""
    if data_fraction * num_clients > 1.0 + 1e-9:
        raise ValueError(
            f"quantity partition infeasible: data_fraction="
            f"{data_fraction} x {num_clients} clients > 1"
        )
    total = min(n, max(num_clients, int(n * data_fraction * num_clients)))
    if total < num_clients:
        raise ValueError(
            f"quantity partition infeasible: {n} rows cannot give "
            f"{num_clients} clients one row each"
        )
    perm = rng.permutation(n)[:total]
    props = rng.dirichlet([alpha] * num_clients)
    # floor over (total - C) spare rows plus one guaranteed row each;
    # the flooring remainder goes to the largest shard so sizes sum to
    # ``total`` exactly.
    sizes = np.floor(props * (total - num_clients)).astype(int) + 1
    sizes[int(np.argmax(sizes))] += total - int(sizes.sum())
    cuts = np.cumsum(sizes)[:-1]
    return [np.asarray(part) for part in np.split(perm, cuts)]


def partition_indices(
    labels: np.ndarray,
    num_clients: int,
    cfg: DataConfig,
) -> list[np.ndarray]:
    """Row indices per client for the index-based schemes
    (``disjoint`` | ``dirichlet`` | ``quantity``), seeded from
    ``cfg.seed_base``; the same seed reproduces the identical index
    sets on every run and every deployment tier.

    ``data_fraction`` is always per-dataset (same convention across
    schemes): each client gets ``frac * n`` rows (exactly for disjoint,
    in expectation for the skewed schemes).
    """
    n = len(labels)
    rng = np.random.default_rng(cfg.seed_base)
    if cfg.partition == "disjoint":
        # data_fraction is per-dataset (same convention as 'sample' and
        # 'dirichlet'): each client gets frac*n rows, disjoint across clients.
        if cfg.data_fraction * num_clients > 1.0 + 1e-9:
            raise ValueError(
                f"disjoint partition infeasible: data_fraction="
                f"{cfg.data_fraction} x {num_clients} clients > 1"
            )
        perm = rng.permutation(n)
        per_client = max(1, int(n * cfg.data_fraction))
        return [
            perm[cid * per_client : (cid + 1) * per_client]
            for cid in range(num_clients)
        ]
    if cfg.partition == "dirichlet":
        return dirichlet_label_indices(
            np.asarray(labels),
            num_clients,
            alpha=cfg.dirichlet_alpha,
            data_fraction=cfg.data_fraction,
            rng=rng,
        )
    if cfg.partition == "quantity":
        return quantity_skew_indices(
            n,
            num_clients,
            alpha=cfg.dirichlet_alpha,
            data_fraction=cfg.data_fraction,
            rng=rng,
        )
    raise ValueError(f"unknown partition scheme {cfg.partition!r}")


# ----------------------------------------------------------- manifest
def partition_manifest(
    client_labels: Sequence[np.ndarray],
    *,
    cfg: DataConfig,
    total_rows: int,
) -> dict:
    """Per-client label histograms for one computed partition — the
    record of exactly what each client saw under a non-IID scheme.
    ``client_labels`` is each client's binary label array (the shard's
    rows, pre train/val/test split)."""
    classes = sorted(
        {int(c) for arr in client_labels for c in np.unique(np.asarray(arr))}
    )
    clients = []
    for cid, arr in enumerate(client_labels):
        arr = np.asarray(arr)
        clients.append(
            {
                "client": cid,
                "rows": int(len(arr)),
                "label_hist": {
                    str(c): int((arr == c).sum()) for c in classes
                },
            }
        )
    return {
        "scheme": cfg.partition,
        "seed": int(cfg.seed_base),
        "alpha": (
            float(cfg.dirichlet_alpha)
            if cfg.partition in ("dirichlet", "quantity")
            else None
        ),
        "data_fraction": float(cfg.data_fraction),
        "num_clients": len(clients),
        "total_rows": int(total_rows),
        "assigned_rows": int(sum(c["rows"] for c in clients)),
        # 'sample' draws independently per client, so shards may overlap
        # (assigned_rows can exceed distinct source rows); the
        # index-based schemes are disjoint by construction.
        "disjoint": cfg.partition != "sample",
        "clients": clients,
    }


def log_manifest(manifest: dict) -> None:
    """One INFO line summarizing the partition (per-client row count +
    label histogram) — the at-a-glance record of how skewed a run was."""
    per = ", ".join(
        f"c{c['client']}:{c['rows']}rows{c['label_hist']}"
        for c in manifest["clients"]
    )
    log.info(
        f"[DATA] partition {manifest['scheme']} (seed {manifest['seed']}"
        + (
            f", alpha {manifest['alpha']}"
            if manifest.get("alpha") is not None
            else ""
        )
        + f"): {manifest['assigned_rows']}/{manifest['total_rows']} rows -> "
        + per
    )


def save_manifest(manifest: dict, path: str) -> str:
    """Write the manifest JSON (atomic replace; reruns overwrite)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2)
    os.replace(tmp, path)
    return path
