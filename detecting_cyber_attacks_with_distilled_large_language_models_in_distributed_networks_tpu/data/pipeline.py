"""Host-side pipeline: texts -> static-shape token arrays -> batch streams.

The reference re-tokenizes every sample on every epoch inside
``Dataset.__getitem__`` on the host (reference client1.py:36-50) and feeds
bs=16 via a torch DataLoader (client1.py:370-372). Here everything is
tokenized once into ``[N, max_len]`` int32 arrays; epochs are host-side
permutations over device-ready numpy, so the accelerator never waits on
Python string work.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, Sequence

import numpy as np

from .cicids import ClientSplits, SplitArrays
from .tokenizer import WordPieceTokenizer


@dataclass
class TokenizedSplit:
    input_ids: np.ndarray  # [N, L] int32
    attention_mask: np.ndarray  # [N, L] int32
    labels: np.ndarray  # [N] int32

    def __len__(self) -> int:
        return len(self.labels)

    def take(self, idx: np.ndarray) -> "TokenizedSplit":
        return TokenizedSplit(
            self.input_ids[idx], self.attention_mask[idx], self.labels[idx]
        )


@dataclass
class TokenizedClient:
    client_id: int
    train: TokenizedSplit
    val: TokenizedSplit
    test: TokenizedSplit


def tokenize_split(
    split: SplitArrays, tok: WordPieceTokenizer, max_len: int
) -> TokenizedSplit:
    enc = tok.batch_encode(split.texts, max_len=max_len)
    return TokenizedSplit(
        enc["input_ids"], enc["attention_mask"], split.labels.astype(np.int32)
    )


def tokenize_client(
    splits: ClientSplits, tok: WordPieceTokenizer, max_len: int
) -> TokenizedClient:
    return TokenizedClient(
        splits.client_id,
        tokenize_split(splits.train, tok, max_len),
        tokenize_split(splits.val, tok, max_len),
        tokenize_split(splits.test, tok, max_len),
    )


def batch_iterator(
    split: TokenizedSplit,
    batch_size: int,
    *,
    shuffle: bool = False,
    seed: int | None = None,
    drop_remainder: bool = True,
) -> Iterator[dict[str, np.ndarray]]:
    """Epoch over one split. With ``drop_remainder`` every batch has the same
    static shape (one XLA compilation); the final short batch of the
    reference's DataLoader would retrigger compilation on TPU."""
    n = len(split)
    order = np.arange(n)
    if shuffle:
        np.random.default_rng(seed).shuffle(order)
    stop = n - (n % batch_size) if drop_remainder else n
    for i in range(0, stop, batch_size):
        idx = order[i : i + batch_size]
        yield {
            "input_ids": split.input_ids[idx],
            "attention_mask": split.attention_mask[idx],
            "labels": split.labels[idx],
        }


def num_batches(n: int, batch_size: int, drop_remainder: bool = True) -> int:
    return n // batch_size if drop_remainder else -(-n // batch_size)


def shard_rows(batch: dict, sharding, replicated) -> dict:
    """Place a host batch onto a device mesh with rows sharded over the
    sharding's leading mesh axis (every value's axis 0 is the batch row).

    A batch whose row count does not divide the axis — the final short
    batch under ``drop_remainder=False`` — is placed REPLICATED instead:
    the math is identical (each device computes the full small batch), so
    the trajectory matches the single-device engine exactly, at the cost
    of redundant FLOPs on one batch per epoch."""
    import jax

    rows = len(next(iter(batch.values())))
    n_shards = sharding.mesh.shape[sharding.spec[0]]
    target = sharding if rows % n_shards == 0 else replicated
    return {k: jax.device_put(v, target) for k, v in batch.items()}


def pad_split_to_batch(
    split: TokenizedSplit, batch_size: int, pad_id: int = 0
) -> tuple[TokenizedSplit, np.ndarray]:
    """Pad a split with PAD rows up to a batch multiple; returns the padded
    split plus a ``[N_padded]`` validity mask. Used for eval, where every
    example must be counted exactly once with static shapes. ``pad_id`` must
    be the tokenizer's pad id (index of ``[PAD]`` in the active vocab)."""
    n = len(split)
    n_pad = (-n) % batch_size
    if n_pad == 0:
        return split, np.ones(n, dtype=np.int32)
    pad_rows = np.full(
        (n_pad, split.input_ids.shape[1]), pad_id, dtype=split.input_ids.dtype
    )
    zero_mask = np.zeros((n_pad, split.input_ids.shape[1]), dtype=split.attention_mask.dtype)
    padded = TokenizedSplit(
        np.concatenate([split.input_ids, pad_rows]),
        np.concatenate([split.attention_mask, zero_mask]),
        np.concatenate([split.labels, np.zeros(n_pad, dtype=split.labels.dtype)]),
    )
    valid = np.concatenate([np.ones(n, np.int32), np.zeros(n_pad, np.int32)])
    return padded, valid


def stack_clients(
    clients: Sequence[TokenizedSplit], n_rows: int | None = None
) -> TokenizedSplit:
    """Stack per-client splits into ``[C, N, ...]`` arrays with a common N
    (min across clients unless given) — the feed format for the stacked
    federated train step, where axis 0 shards over the ``clients`` mesh axis.

    TRUNCATES rows beyond the common N; for unequal clients prefer
    :func:`stack_clients_ragged`, which pads to the fleet max with validity
    masks so every client's full split enters training."""
    if n_rows is None:
        n_rows = min(len(c) for c in clients)
    return TokenizedSplit(
        np.stack([c.input_ids[:n_rows] for c in clients]),
        np.stack([c.attention_mask[:n_rows] for c in clients]),
        np.stack([c.labels[:n_rows] for c in clients]),
    )


@dataclass
class StackedClients:
    """Ragged per-client train splits stacked to a common (fleet-max) row
    count with per-row validity — the lossless feed format for the stacked
    federated train step. Unlike :func:`stack_clients` (fleet-min
    truncation), every client's every row enters training; pad rows carry
    ``row_valid == 0`` and contribute nothing to losses or gradients.

    The reference's N independent processes each consume 100% of their own
    (differently sized) samples (reference client1.py:89 vs client2.py:84);
    this is the SPMD shape of that exact semantic."""

    split: TokenizedSplit  # [C, N_max, ...]
    row_valid: np.ndarray  # [C, N_max] int32 0/1
    n_rows: np.ndarray  # [C] true per-client row counts

    @property
    def labels(self) -> np.ndarray:
        return self.split.labels

    def __len__(self) -> int:
        return len(self.n_rows)


def stack_clients_ragged(
    clients: Sequence[TokenizedSplit],
    *,
    pad_id: int = 0,
    target_rows: int | None = None,
) -> StackedClients:
    """Stack unequal per-client splits into ``[C, N_max, ...]`` arrays plus
    a validity matrix, padding short clients with PAD rows (attention mask
    all zero, label 0, valid 0). ``target_rows`` lets multi-host callers
    pass the GLOBAL max split length so every host agrees on N_max (the
    stacked train loop is a sequence of collectives)."""
    n_rows = np.array([len(c) for c in clients], np.int64)
    target = int(n_rows.max()) if len(clients) else 0
    if target_rows is not None:
        if target_rows < target:
            raise ValueError(
                f"target_rows={target_rows} < local max split length {target}"
            )
        target = target_rows
    ids, masks, labels, valid = [], [], [], []
    for c in clients:
        extra = target - len(c)
        L = c.input_ids.shape[1]
        ids.append(
            np.concatenate(
                [c.input_ids, np.full((extra, L), pad_id, c.input_ids.dtype)]
            )
        )
        masks.append(
            np.concatenate(
                [c.attention_mask, np.zeros((extra, L), c.attention_mask.dtype)]
            )
        )
        labels.append(
            np.concatenate([c.labels, np.zeros(extra, c.labels.dtype)])
        )
        valid.append(
            np.concatenate([np.ones(len(c), np.int32), np.zeros(extra, np.int32)])
        )
    return StackedClients(
        TokenizedSplit(np.stack(ids), np.stack(masks), np.stack(labels)),
        np.stack(valid),
        n_rows,
    )
