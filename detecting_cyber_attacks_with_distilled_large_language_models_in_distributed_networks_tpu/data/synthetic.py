"""Synthetic CICIDS2017-style flow generator.

The reference bundles a 2,885-row all-BENIGN stub of the real ~225k-row
CICIDS2017 Friday-DDoS-day CSV (see SURVEY.md §0) — useless for exercising the
classifier. This generator produces a schema-compatible frame with *separable*
BENIGN vs DDoS populations (DDoS flows: high packet rates, short durations,
large forward counts — the statistical signature of the real attack day), so
tests and benchmarks can verify learning end-to-end without the real dataset.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from .textualize import FLOW_TEXT_COLUMNS

#: Full 79-column CICIDS2017 header (the 10 rendered columns plus a Label
#: column matter; the rest are schema filler kept for loader parity).
_EXTRA_COLUMNS: tuple[str, ...] = (
    "Fwd Packet Length Mean",
    "Fwd Packet Length Std",
    "Bwd Packet Length Max",
    "Bwd Packet Length Min",
    "Flow IAT Mean",
    "Flow IAT Std",
)


def make_synthetic_flows(
    n_rows: int = 2000,
    ddos_fraction: float = 0.5,
    seed: int = 0,
    inf_fraction: float = 0.01,
    nan_fraction: float = 0.01,
) -> pd.DataFrame:
    """Generate a separable BENIGN/DDoS flow table.

    A sprinkle of ±inf and NaN exercises the imputation path
    (reference client1.py:87-88).
    """
    rng = np.random.default_rng(seed)
    n_ddos = int(n_rows * ddos_fraction)
    n_benign = n_rows - n_ddos

    def _mix(benign_sampler, ddos_sampler):
        return np.concatenate([benign_sampler(n_benign), ddos_sampler(n_ddos)])

    cols: dict[str, np.ndarray] = {}
    cols["Destination Port"] = _mix(
        lambda n: rng.choice([53, 443, 8080, 22, 3389], size=n),
        lambda n: rng.choice([80, 443], size=n),
    ).astype(np.int64)
    cols["Flow Duration"] = _mix(
        lambda n: rng.integers(1_000, 10_000_000, size=n),
        lambda n: rng.integers(1, 5_000, size=n),
    ).astype(np.int64)
    cols["Total Fwd Packets"] = _mix(
        lambda n: rng.integers(1, 30, size=n),
        lambda n: rng.integers(100, 2_000, size=n),
    ).astype(np.int64)
    cols["Total Backward Packets"] = _mix(
        lambda n: rng.integers(1, 30, size=n),
        lambda n: rng.integers(0, 3, size=n),
    ).astype(np.int64)
    cols["Total Length of Fwd Packets"] = _mix(
        lambda n: rng.integers(0, 5_000, size=n),
        lambda n: rng.integers(50_000, 500_000, size=n),
    ).astype(np.int64)
    cols["Total Length of Bwd Packets"] = _mix(
        lambda n: rng.integers(0, 5_000, size=n),
        lambda n: rng.integers(0, 200, size=n),
    ).astype(np.int64)
    cols["Fwd Packet Length Max"] = _mix(
        lambda n: rng.integers(0, 1_500, size=n),
        lambda n: rng.integers(1_000, 1_500, size=n),
    ).astype(np.int64)
    cols["Fwd Packet Length Min"] = _mix(
        lambda n: rng.integers(0, 100, size=n),
        lambda n: rng.integers(500, 1_000, size=n),
    ).astype(np.int64)
    cols["Flow Bytes/s"] = np.round(
        _mix(
            lambda n: rng.uniform(10, 1e5, size=n),
            lambda n: rng.uniform(1e6, 5e7, size=n),
        ),
        4,
    )
    cols["Flow Packets/s"] = np.round(
        _mix(
            lambda n: rng.uniform(0.1, 1e3, size=n),
            lambda n: rng.uniform(1e4, 1e6, size=n),
        ),
        4,
    )
    for name in _EXTRA_COLUMNS:
        cols[name] = np.round(rng.uniform(0, 1_000, size=n_rows), 4)

    labels = np.array(["BENIGN"] * n_benign + ["DDoS"] * n_ddos)

    # Inject ±inf / NaN into float columns only (imputation targets).
    float_cols = ["Flow Bytes/s", "Flow Packets/s", *list(_EXTRA_COLUMNS)]
    for name in float_cols:
        arr = cols[name].astype(np.float64)
        bad = rng.random(n_rows)
        arr[bad < inf_fraction] = np.inf
        arr[(bad >= inf_fraction) & (bad < inf_fraction + nan_fraction)] = np.nan
        cols[name] = arr

    df = pd.DataFrame(cols)
    df["Label"] = labels
    # Shuffle rows so class blocks don't align with sampling order.
    perm = rng.permutation(n_rows)
    return df.iloc[perm].reset_index(drop=True)


#: CIC-DDoS2019 attack-class label vocabulary (subset of the real set).
DDOS2019_ATTACKS: tuple[str, ...] = (
    "DrDoS_DNS",
    "DrDoS_LDAP",
    "DrDoS_NTP",
    "DrDoS_UDP",
    "Syn",
    "UDP-lag",
)


def make_synthetic_ddos2019(
    n_rows: int = 2000,
    attack_fraction: float = 0.5,
    seed: int = 0,
    **kwargs,
) -> pd.DataFrame:
    """CIC-DDoS2019-style frame: same CICFlowMeter schema as CICIDS2017
    (shared template, data/datasets.py) but per-attack-class labels, so the
    binary map is ``Label != 'BENIGN'``."""
    df = make_synthetic_flows(
        n_rows, ddos_fraction=attack_fraction, seed=seed, **kwargs
    )
    rng = np.random.default_rng(seed + 1)
    attack = df["Label"].to_numpy() == "DDoS"
    labels = df["Label"].to_numpy().astype(object)
    labels[attack] = rng.choice(DDOS2019_ATTACKS, size=int(attack.sum()))
    df["Label"] = labels
    return df


def make_synthetic_unsw(
    n_rows: int = 2000,
    attack_fraction: float = 0.5,
    seed: int = 0,
    inf_fraction: float = 0.01,
    nan_fraction: float = 0.01,
) -> pd.DataFrame:
    """UNSW-NB15-style frame with separable normal/attack populations over
    the 10 templated columns (data/datasets.py UNSW_TEMPLATE) plus the
    official ``attack_cat``/``label`` tail columns."""
    rng = np.random.default_rng(seed)
    n_attack = int(n_rows * attack_fraction)
    n_normal = n_rows - n_attack

    def _mix(normal_sampler, attack_sampler):
        return np.concatenate([normal_sampler(n_normal), attack_sampler(n_attack)])

    cols: dict[str, np.ndarray] = {}
    cols["dur"] = np.round(
        _mix(
            lambda n: rng.uniform(0.05, 30.0, size=n),
            lambda n: rng.uniform(1e-4, 0.02, size=n),
        ),
        6,
    )
    cols["proto"] = _mix(
        lambda n: rng.choice(["tcp", "udp", "arp"], size=n),
        lambda n: rng.choice(["tcp", "udp"], size=n),
    )
    cols["service"] = _mix(
        lambda n: rng.choice(["http", "dns", "smtp", "-"], size=n),
        lambda n: rng.choice(["dns", "-"], size=n),
    )
    cols["spkts"] = _mix(
        lambda n: rng.integers(2, 40, size=n),
        lambda n: rng.integers(100, 4_000, size=n),
    ).astype(np.int64)
    cols["dpkts"] = _mix(
        lambda n: rng.integers(2, 40, size=n),
        lambda n: rng.integers(0, 3, size=n),
    ).astype(np.int64)
    cols["sbytes"] = _mix(
        lambda n: rng.integers(100, 10_000, size=n),
        lambda n: rng.integers(50_000, 1_000_000, size=n),
    ).astype(np.int64)
    cols["dbytes"] = _mix(
        lambda n: rng.integers(100, 10_000, size=n),
        lambda n: rng.integers(0, 500, size=n),
    ).astype(np.int64)
    cols["rate"] = np.round(
        _mix(
            lambda n: rng.uniform(0.5, 500.0, size=n),
            lambda n: rng.uniform(5e4, 1e6, size=n),
        ),
        4,
    )
    cols["sload"] = np.round(
        _mix(
            lambda n: rng.uniform(1e2, 1e6, size=n),
            lambda n: rng.uniform(1e8, 5e9, size=n),
        ),
        4,
    )
    cols["dload"] = np.round(
        _mix(
            lambda n: rng.uniform(1e2, 1e6, size=n),
            lambda n: rng.uniform(0, 1e3, size=n),
        ),
        4,
    )
    # Schema-filler tail columns from the official feature list.
    for name in ("sttl", "dttl", "sloss", "dloss"):
        cols[name] = rng.integers(0, 255, size=n_rows).astype(np.int64)
    for name in ("sinpkt", "dinpkt", "sjit", "djit"):
        arr = np.round(rng.uniform(0, 1_000, size=n_rows), 4)
        bad = rng.random(n_rows)
        arr[bad < inf_fraction] = np.inf
        arr[(bad >= inf_fraction) & (bad < inf_fraction + nan_fraction)] = np.nan
        cols[name] = arr

    cols["attack_cat"] = _mix(
        lambda n: np.array(["Normal"] * n),
        lambda n: rng.choice(["Generic", "Exploits", "DoS", "Fuzzers"], size=n),
    )
    cols["label"] = np.concatenate(
        [np.zeros(n_normal, np.int64), np.ones(n_attack, np.int64)]
    )

    df = pd.DataFrame(cols)
    perm = rng.permutation(n_rows)
    return df.iloc[perm].reset_index(drop=True)


_GENERATORS = {
    "cicids2017": make_synthetic_flows,
    "cicddos2019": make_synthetic_ddos2019,
    "unswnb15": make_synthetic_unsw,
}


def make_synthetic(dataset: str, n_rows: int = 2000, **kwargs) -> pd.DataFrame:
    """Schema-dispatched synthetic generator (datasets registry names)."""
    try:
        gen = _GENERATORS[dataset]
    except KeyError:
        raise ValueError(
            f"no synthetic generator for dataset {dataset!r}; "
            f"have {sorted(_GENERATORS)}"
        ) from None
    return gen(n_rows, **kwargs)


def write_synthetic_csv(path: str, dataset: str = "cicids2017", **kwargs) -> pd.DataFrame:
    df = make_synthetic(dataset, **kwargs)
    df.to_csv(path, index=False)
    return df


__all__ = [
    "make_synthetic_flows",
    "make_synthetic_ddos2019",
    "make_synthetic_unsw",
    "make_synthetic",
    "write_synthetic_csv",
    "FLOW_TEXT_COLUMNS",
    "DDOS2019_ATTACKS",
]
