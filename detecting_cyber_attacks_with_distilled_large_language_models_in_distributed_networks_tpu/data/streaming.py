"""Two-pass streaming CSV -> per-client token arrays, for corpora > RAM.

The reference loads its whole CSV into pandas at once (client1.py:85) —
fine for the bundled ~225k-row file, impossible for the real CIC-DDoS2019
exports (tens of GB). This pipeline never materializes the frame:

* **Pass 1** (cheap scan): row count, per-column finite sums/counts for the
  reference's ``±inf -> NaN -> column-mean`` imputation (client1.py:86-88),
  per-column dtype facts (so pass 2 can pin dtypes — pandas infers PER
  CHUNK, which would render ``0`` in one chunk and ``0.0`` in another and
  silently diverge from the whole-file inference of the in-memory path),
  and the binary label vector (4 bytes/row).
* **Partition plan** (in memory, labels only): per-client row indices via
  the same ``disjoint``/``dirichlet`` partitioners as the in-memory path,
  then the reference's 60/20/20 split per client; destinations are stored
  as row-sorted numpy arrays, located per chunk with ``searchsorted``.
* **Pass 2**: impute each chunk with the pass-1 means, render the dataset's
  text template, batch-encode (the native WordPiece path), and scatter rows
  straight into preallocated ``[N_split, max_len]`` int32 arrays.

Peak memory is the OUTPUT token arrays plus the destination index arrays
(~17 bytes/selected row) plus one chunk — independent of the CSV size. The
``sample`` scheme uses index-permutation sampling (the corpus convention)
rather than ``df.sample``; use the in-memory path when exact pandas
sampling parity matters.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np
import pandas as pd

from ..config import DataConfig
from .cicids import partition_indices, train_val_test_split
from .datasets import DatasetSpec, get_dataset
from .pipeline import TokenizedClient, TokenizedSplit
from .tokenizer import WordPieceTokenizer

_SPLIT_NAMES = ("train", "val", "test")


class _Pass1:
    """Streaming scan results."""

    def __init__(
        self,
        n_rows: int,
        means: dict[str, float],
        labels: np.ndarray,
        float_cols: list[str],
    ):
        self.n_rows = n_rows
        self.means = means
        self.labels = labels
        #: Columns pass 2 must read as float64: any chunk saw a float dtype
        #: or a non-finite value. Whole-file pandas inference would promote
        #: exactly these (one NaN anywhere floats the column), so pinning
        #: them keeps string rendering identical to the in-memory path.
        self.float_cols = float_cols


def _chunks(
    path: str, chunk_rows: int, dtype: dict | None = None
) -> Iterator[pd.DataFrame]:
    for chunk in pd.read_csv(
        path, skipinitialspace=True, chunksize=chunk_rows, dtype=dtype
    ):
        chunk.columns = [c.strip() for c in chunk.columns]
        yield chunk


def _scan(path: str, spec: DatasetSpec, cfg: DataConfig, chunk_rows: int) -> _Pass1:
    sums: dict[str, float] = {}
    counts: dict[str, int] = {}
    saw_float: set[str] = set()
    saw_nonnumeric: set[str] = set()
    labels: list[np.ndarray] = []
    n = 0
    for chunk in _chunks(path, chunk_rows):
        n += len(chunk)
        if spec.label_kind == "positive":
            labels.append(
                spec.binary_labels(
                    chunk,
                    label_column=cfg.label_column,
                    positive_value=cfg.positive_label,
                )
            )
        else:
            labels.append(spec.binary_labels(chunk))
        for col in chunk.columns:
            if not pd.api.types.is_numeric_dtype(chunk[col]):
                saw_nonnumeric.add(col)
                continue
            if pd.api.types.is_float_dtype(chunk[col]):
                saw_float.add(col)
            vals = chunk[col].to_numpy(dtype=np.float64, copy=False)
            finite = np.isfinite(vals)
            if not finite.all():
                saw_float.add(col)
            sums[col] = sums.get(col, 0.0) + float(vals[finite].sum())
            counts[col] = counts.get(col, 0) + int(finite.sum())
    # A column that is non-numeric in ANY chunk is non-numeric whole-file
    # (pandas would infer object): exclude it from imputation entirely.
    means = {
        c: (sums[c] / counts[c] if counts[c] else 0.0)
        for c in sums
        if c not in saw_nonnumeric
    }
    float_cols = sorted(saw_float - saw_nonnumeric)
    return _Pass1(
        n,
        means,
        np.concatenate(labels) if labels else np.zeros(0, np.int32),
        float_cols,
    )


def _impute(chunk: pd.DataFrame, means: dict[str, float]) -> pd.DataFrame:
    for col, mean in means.items():
        if col not in chunk.columns:
            continue
        vals = chunk[col].to_numpy(dtype=np.float64)
        bad = ~np.isfinite(vals)
        if bad.any():
            vals = vals.copy()  # to_numpy may return a read-only view
            vals[bad] = mean
            chunk[col] = vals
    return chunk


def _client_split_indices(
    labels: np.ndarray, num_clients: int, cfg: DataConfig
) -> list[dict[str, np.ndarray]]:
    """Per-client {train,val,test} -> global row indices."""
    n = len(labels)
    if cfg.partition == "sample":
        per_client = max(1, int(round(n * cfg.data_fraction)))
        parts = [
            np.random.RandomState(cfg.client_seed(cid)).permutation(n)[:per_client]
            for cid in range(num_clients)
        ]
    else:
        parts = partition_indices(labels, num_clients, cfg)
    out = []
    for cid, rows in enumerate(parts):
        tr, va, te = train_val_test_split(
            len(rows), cfg.client_seed(cid), cfg.val_fraction, cfg.test_fraction
        )
        out.append({"train": rows[tr], "val": rows[va], "test": rows[te]})
    return out


def stream_client_tokens_for(
    path: str,
    cfg: DataConfig,
    num_clients: int,
    tok: WordPieceTokenizer,
    client_ids: list[int],
    *,
    max_len: int | None = None,
    chunk_rows: int = 100_000,
) -> tuple[list[TokenizedClient], list[dict[str, int]]]:
    """Streamed tokenization for a SUBSET of the fleet's clients, plus the
    GLOBAL per-client split sizes.

    The partition plan always covers all ``num_clients`` (it must be
    globally consistent — under multi-host every process computes the
    identical plan from the identical label scan), but token arrays are
    materialized only for ``client_ids``: each host streams its own pass
    over the CSV and pays memory only for its own clients. Returns
    ``(tokenized clients in client_ids order,
    [{"train": n, "val": n, "test": n} for every global client])``."""
    max_len = cfg.max_len if max_len is None else max_len
    wanted = list(client_ids)
    # Validate BEFORE the full-file scan: a bad id must fail instantly,
    # not after minutes of I/O on a multi-GB CSV.
    bad = [c for c in wanted if not 0 <= c < num_clients]
    if bad:
        raise ValueError(f"client_ids {bad} outside [0, {num_clients})")
    spec = get_dataset(cfg.dataset)
    scan = _scan(path, spec, cfg, chunk_rows)
    plans = _client_split_indices(scan.labels, num_clients, cfg)
    sizes = [
        {name: int(len(plan[name])) for name in _SPLIT_NAMES} for plan in plans
    ]

    # Destination arrays (allocated up front, LOCAL clients only) + a flat,
    # row-sorted index: (global_row, local_client, split, position) in
    # parallel numpy arrays — a row may land in several destinations under
    # the 'sample' scheme.
    dest: list[dict[str, TokenizedSplit]] = []
    rows_l, client_l, split_l, pos_l = [], [], [], []
    for local, cid in enumerate(wanted):
        plan = plans[cid]
        splits = {}
        for sid, name in enumerate(_SPLIT_NAMES):
            rows = plan[name]
            m = len(rows)
            splits[name] = TokenizedSplit(
                np.full((m, max_len), tok.pad_id, np.int32),
                np.zeros((m, max_len), np.int32),
                scan.labels[rows].astype(np.int32),
            )
            rows_l.append(rows.astype(np.int64))
            client_l.append(np.full(m, local, np.int32))
            split_l.append(np.full(m, sid, np.int8))
            pos_l.append(np.arange(m, dtype=np.int64))
        dest.append(splits)
    rows_all = np.concatenate(rows_l) if rows_l else np.zeros(0, np.int64)
    order = np.argsort(rows_all, kind="stable")
    rows_all = rows_all[order]
    client_all = np.concatenate(client_l)[order] if rows_l else np.zeros(0, np.int32)
    split_all = np.concatenate(split_l)[order] if rows_l else np.zeros(0, np.int8)
    pos_all = np.concatenate(pos_l)[order] if rows_l else np.zeros(0, np.int64)

    dtype_spec = {c: np.float64 for c in scan.float_cols}
    row_base = 0
    for chunk in _chunks(path, chunk_rows, dtype=dtype_spec or None):
        lo = np.searchsorted(rows_all, row_base)
        hi = np.searchsorted(rows_all, row_base + len(chunk))
        if hi > lo:
            hit_rows = rows_all[lo:hi] - row_base  # local, may repeat
            uniq, inverse = np.unique(hit_rows, return_inverse=True)
            sub = _impute(chunk.iloc[uniq].copy(), scan.means)
            texts = spec.render_texts(sub)
            enc = tok.batch_encode(texts, max_len=max_len)
            for k in range(hi - lo):
                split = dest[client_all[lo + k]][_SPLIT_NAMES[split_all[lo + k]]]
                src = inverse[k]
                p = pos_all[lo + k]
                split.input_ids[p] = enc["input_ids"][src]
                split.attention_mask[p] = enc["attention_mask"][src]
        row_base += len(chunk)

    clients = [
        TokenizedClient(cid, d["train"], d["val"], d["test"])
        for cid, d in zip(wanted, dest)
    ]
    return clients, sizes


def stream_client_tokens(
    path: str,
    cfg: DataConfig,
    num_clients: int,
    tok: WordPieceTokenizer,
    *,
    max_len: int | None = None,
    chunk_rows: int = 100_000,
) -> list[TokenizedClient]:
    """Streamed equivalent of ``make_all_client_splits`` + ``tokenize_client``
    for the index-based partition schemes; peak memory is the output arrays
    plus the destination index plus one chunk of the CSV."""
    clients, _ = stream_client_tokens_for(
        path,
        cfg,
        num_clients,
        tok,
        list(range(num_clients)),
        max_len=max_len,
        chunk_rows=chunk_rows,
    )
    return clients
