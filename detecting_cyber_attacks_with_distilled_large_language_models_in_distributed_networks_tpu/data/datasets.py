"""Dataset registry: CICIDS2017, CIC-DDoS2019, UNSW-NB15, and mixed corpora.

The reference is hard-wired to one CICIDS2017 CSV with a ``'DDoS' -> 1``
label map (reference client1.py:84-93); BASELINE.json config 5 asks for a
"CIC-DDoS2019 + UNSW-NB15 mixed corpus" fleet. Each dataset here is a
:class:`DatasetSpec`: an English text template over its flow columns (the
same feature-to-text trick as reference client1.py:68-81, adapted per
schema) plus binary-label semantics:

* ``cicids2017``  — ``Label == 'DDoS'`` -> 1 (reference client1.py:91).
* ``cicddos2019`` — CICFlowMeter schema shared with CICIDS2017, but labels
  are per-attack classes (``DrDoS_DNS``, ``Syn``, ...), so the binary map is
  ``Label != 'BENIGN'`` -> 1.
* ``unswnb15``    — different schema entirely (dur/proto/service/spkts/...);
  the official CSVs carry a 0/1 ``label`` column directly.

A :class:`Corpus` is the schema-erased form — texts + binary labels +
per-row source ids — which is what mixed-dataset federation partitions
over (the per-client pipeline downstream of textualization is identical
for every dataset).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np
import pandas as pd

from .textualize import CICIDS_TEMPLATE, render_template

#: UNSW-NB15 text template over 10 of its 49 features, in the same English
#: sentence style as the CICIDS2017 template (reference client1.py:68-81).
UNSW_TEMPLATE: tuple[tuple[str, str, str], ...] = (
    ("Protocol is ", "proto", ". "),
    ("Service is ", "service", ". "),
    ("Flow duration is ", "dur", " seconds. "),
    ("Source to destination packets are ", "spkts", ". "),
    ("Destination to source packets are ", "dpkts", ". "),
    ("Source to destination bytes are ", "sbytes", " bytes. "),
    ("Destination to source bytes are ", "dbytes", " bytes. "),
    ("Packet rate is ", "rate", " per second. "),
    ("Source load is ", "sload", " bits per second. "),
    ("Destination load is ", "dload", " bits per second."),
)


@dataclass(frozen=True)
class DatasetSpec:
    """One dataset's text template + label semantics."""

    name: str
    template: tuple[tuple[str, str, str], ...]
    label_column: str
    #: "positive"   — label == positive_value -> 1 (CICIDS2017 semantics)
    #: "not_benign" — label != benign_value  -> 1 (multi-attack-class sets)
    #: "int"        — label column already 0/1
    #: "multiclass" — label -> index into ``classes`` (K-class plane;
    #:                class 0 is benign by convention, so the binary map
    #:                stays ``label != benign_value``)
    label_kind: str
    positive_value: str = "DDoS"
    benign_value: str = "BENIGN"
    #: Ordered class vocabulary for the K-class plane (``multiclass``
    #: specs only). Class 0 MUST be the benign value — every consumer
    #: (serving score plane, supervised join) binarizes as ``!= 0``.
    classes: tuple[str, ...] | None = None

    def render_texts(self, df: pd.DataFrame) -> list[str]:
        missing = [c for _, c, _ in self.template if c not in df.columns]
        if missing:
            raise KeyError(
                f"dataset {self.name!r}: CSV is missing template columns "
                f"{missing} (have {list(df.columns)[:8]}...)"
            )
        return render_template(df, self.template)

    def binary_labels(
        self,
        df: pd.DataFrame,
        *,
        label_column: str | None = None,
        positive_value: str | None = None,
    ) -> np.ndarray:
        col = label_column or self.label_column
        if col not in df.columns:
            raise KeyError(f"dataset {self.name!r}: no label column {col!r}")
        if self.label_kind == "positive":
            pos = positive_value or self.positive_value
            return (df[col] == pos).to_numpy().astype(np.int32)
        if self.label_kind in ("not_benign", "multiclass"):
            return (df[col] != self.benign_value).to_numpy().astype(np.int32)
        if self.label_kind == "int":
            return df[col].to_numpy().astype(np.int32)
        raise ValueError(f"unknown label_kind {self.label_kind!r}")

    def class_labels(self, df: pd.DataFrame) -> np.ndarray:
        """K-class label indices into ``classes`` (``multiclass`` specs).

        Strays fail loudly: a label value outside the declared vocabulary
        silently mapped to some class would corrupt every per-class count
        downstream."""
        if self.label_kind != "multiclass" or not self.classes:
            raise ValueError(
                f"dataset {self.name!r} is not a multiclass spec"
            )
        col = self.label_column
        if col not in df.columns:
            raise KeyError(f"dataset {self.name!r}: no label column {col!r}")
        index = {v: i for i, v in enumerate(self.classes)}
        values = df[col].astype(str).to_numpy()
        stray = sorted({v for v in values if v not in index})
        if stray:
            raise ValueError(
                f"dataset {self.name!r}: labels {stray[:8]} not in the "
                f"declared class vocabulary {list(self.classes)}"
            )
        return np.array([index[v] for v in values], dtype=np.int32)

    def labels(self, df: pd.DataFrame) -> np.ndarray:
        """The spec's native label array: K-class indices for multiclass
        specs, 0/1 otherwise — what :func:`corpus_from_frame` feeds the
        (K-generic) training pipeline."""
        if self.label_kind == "multiclass":
            return self.class_labels(df)
        return self.binary_labels(df)

    @property
    def n_classes(self) -> int:
        return len(self.classes) if self.classes else 2

    @property
    def feature_columns(self) -> tuple[str, ...]:
        return tuple(c for _, c, _ in self.template)


CICIDS2017 = DatasetSpec(
    name="cicids2017",
    template=CICIDS_TEMPLATE,
    label_column="Label",
    label_kind="positive",
    positive_value="DDoS",
)

CICDDOS2019 = DatasetSpec(
    name="cicddos2019",
    template=CICIDS_TEMPLATE,  # same CICFlowMeter feature schema
    label_column="Label",
    label_kind="not_benign",
    benign_value="BENIGN",
)

UNSWNB15 = DatasetSpec(
    name="unswnb15",
    template=UNSW_TEMPLATE,
    label_column="label",
    label_kind="int",
)

#: The multi-class CICIDS attack-day preset (ISSUE 18): the CIC-DDoS2019
#: day keeps per-attack labels instead of collapsing them to 0/1 — the
#: K-class plane the generalized train/eval head consumes. Class 0 is
#: BENIGN; the attack order matches data/synthetic.py DDOS2019_ATTACKS
#: so the synthetic generator round-trips without a remap.
CICDDOS2019_MC = DatasetSpec(
    name="cicddos2019-mc",
    template=CICIDS_TEMPLATE,
    label_column="Label",
    label_kind="multiclass",
    benign_value="BENIGN",
    classes=(
        "BENIGN",
        "DrDoS_DNS",
        "DrDoS_LDAP",
        "DrDoS_NTP",
        "DrDoS_UDP",
        "Syn",
        "UDP-lag",
    ),
)

DATASETS: dict[str, DatasetSpec] = {
    s.name: s for s in (CICIDS2017, CICDDOS2019, UNSWNB15, CICDDOS2019_MC)
}


def get_dataset(name: str) -> DatasetSpec:
    try:
        return DATASETS[name]
    except KeyError:
        raise ValueError(
            f"unknown dataset {name!r}; registered: {sorted(DATASETS)}"
        ) from None


#: Label values that occur only in CIC-DDoS2019 exports (beyond the DrDoS_*
#: prefix family, which is matched by prefix).
_DDOS2019_ONLY_LABELS = frozenset(
    {"Syn", "TFTP", "MSSQL", "NetBIOS", "LDAP", "Portmap", "UDP", "UDPLag",
     "UDP-lag", "WebDDoS"}
)


def detect_dataset(df: pd.DataFrame) -> DatasetSpec:
    """Schema sniffing for ``--source path`` entries without an explicit name.

    UNSW-NB15 is structurally distinct; CICIDS2017 vs CIC-DDoS2019 share the
    CICFlowMeter schema and are told apart by their label vocabulary:
    CIC-DDoS2019 names specific DDoS attacks (``DrDoS_*``, ``Syn``, ...).
    Everything else — including real CICIDS2017 exports whose labels span
    PortScan/Bot/DoS Hulk/etc. — keeps the reference's CICIDS2017 semantics
    (only the exact label ``'DDoS'`` maps to 1, client1.py:91), so non-DDoS
    attack rows stay 0 exactly as the reference would label them.
    """
    cols = set(df.columns)
    if {"dur", "spkts", "dpkts"} <= cols:
        return UNSWNB15
    if "Label" in cols:
        values = set(map(str, pd.unique(df["Label"])))
        if any(v.startswith("DrDoS") for v in values) or (
            values & _DDOS2019_ONLY_LABELS
        ):
            return CICDDOS2019
        return CICIDS2017
    raise ValueError(
        "cannot detect dataset: no UNSW-NB15 columns and no 'Label' column "
        f"(have {sorted(cols)[:10]}...)"
    )


# ------------------------------------------------------------------ corpus
@dataclass
class Corpus:
    """Schema-erased training corpus: texts + binary labels + provenance."""

    texts: list[str]
    labels: np.ndarray  # [N] int32
    source: np.ndarray  # [N] int32 — index into source_names
    source_names: tuple[str, ...] = ()

    def __len__(self) -> int:
        return len(self.texts)

    def __post_init__(self) -> None:
        if not (len(self.texts) == len(self.labels) == len(self.source)):
            raise ValueError(
                f"corpus length mismatch: {len(self.texts)} texts, "
                f"{len(self.labels)} labels, {len(self.source)} source ids"
            )


def corpus_from_frame(
    df: pd.DataFrame, spec: DatasetSpec, source_id: int = 0
) -> Corpus:
    return Corpus(
        texts=spec.render_texts(df),
        labels=spec.labels(df),
        source=np.full(len(df), source_id, np.int32),
        source_names=(spec.name,),
    )


def concat_corpora(parts: Sequence[Corpus]) -> Corpus:
    """Concatenate per-dataset corpora into one mixed corpus, re-basing each
    part's source ids onto a combined ``source_names`` tuple."""
    texts: list[str] = []
    labels: list[np.ndarray] = []
    source: list[np.ndarray] = []
    names: list[str] = []
    for part in parts:
        base = len(names)
        names.extend(part.source_names)
        texts.extend(part.texts)
        labels.append(part.labels)
        source.append(part.source + base)
    return Corpus(
        texts,
        np.concatenate(labels) if labels else np.zeros(0, np.int32),
        np.concatenate(source) if source else np.zeros(0, np.int32),
        tuple(names),
    )


def load_mixed_corpus(
    entries: Sequence[tuple[str | None, str]],
) -> Corpus:
    """Load ``(dataset_name_or_None, csv_path)`` entries into one corpus.

    ``None`` dataset names are schema-sniffed via :func:`detect_dataset`.
    Imputation follows the reference (±inf -> NaN -> column mean,
    client1.py:86-88) per source file, matching :func:`load_flow_csv`.
    """
    from .cicids import load_flow_csv

    parts = []
    for name, path in entries:
        df = load_flow_csv(path)
        spec = get_dataset(name) if name else detect_dataset(df)
        parts.append(corpus_from_frame(df, spec))
    return concat_corpora(parts)


def parse_source_arg(arg: str) -> tuple[str | None, str]:
    """CLI ``--source [dataset=]path`` parser."""
    if "=" in arg:
        name, path = arg.split("=", 1)
        get_dataset(name)  # validate early
        return name, path
    return None, arg
