"""Flow-record -> English-sentence rendering.

The reference feeds DistilBERT not raw tabular features but a fixed English
template over 10 of the 79 CICIDS2017 flow columns (reference client1.py:68-81).
The template here is byte-identical — accuracy parity depends on it — but the
implementation is vectorized over whole columns instead of a per-row
``df.apply`` (reference client1.py:90).
"""

from __future__ import annotations

from typing import Mapping, Sequence

import numpy as np
import pandas as pd

#: The 10 flow-feature columns the template renders, in order.
FLOW_TEXT_COLUMNS: tuple[str, ...] = (
    "Destination Port",
    "Flow Duration",
    "Total Fwd Packets",
    "Total Backward Packets",
    "Total Length of Fwd Packets",
    "Total Length of Bwd Packets",
    "Fwd Packet Length Max",
    "Fwd Packet Length Min",
    "Flow Bytes/s",
    "Flow Packets/s",
)

# (prefix, column) pairs; the final fragment carries the trailing period with
# no trailing space, matching the reference template exactly.
_TEMPLATE: tuple[tuple[str, str, str], ...] = (
    ("Destination port is ", "Destination Port", ". "),
    ("Flow duration is ", "Flow Duration", " microseconds. "),
    ("Total forward packets are ", "Total Fwd Packets", ". "),
    ("Total backward packets are ", "Total Backward Packets", ". "),
    ("Total length of forward packets is ", "Total Length of Fwd Packets", " bytes. "),
    ("Total length of backward packets is ", "Total Length of Bwd Packets", " bytes. "),
    ("Maximum forward packet length is ", "Fwd Packet Length Max", ". "),
    ("Minimum forward packet length is ", "Fwd Packet Length Min", ". "),
    ("Flow bytes per second is ", "Flow Bytes/s", ". "),
    ("Flow packets per second is ", "Flow Packets/s", "."),
)

#: Public alias for dataset registrations (data/datasets.py).
CICIDS_TEMPLATE = _TEMPLATE


def render_row(row: Mapping[str, object], template: Sequence[tuple[str, str, str]]) -> str:
    """Render one record through a ``(prefix, column, suffix)`` template."""
    parts = []
    for prefix, col, suffix in template:
        parts.append(f"{prefix}{row[col]}{suffix}")
    return "".join(parts)


def render_template(
    df: pd.DataFrame, template: Sequence[tuple[str, str, str]]
) -> list[str]:
    """Vectorized template rendering for a whole frame.

    Equivalent to ``df.apply(render_row, axis=1).tolist()`` but builds the
    strings column-wise: one str() pass per column rather than one dict
    lookup + f-string per cell.
    """
    n = len(df)
    if n == 0:
        return []
    # One str() pass per column. .tolist() yields python ints/floats whose
    # str() is identical to formatting the numpy scalar in an f-string
    # (e.g. '666666.6667', '54865', 'nan'), so parity with render_row holds.
    col_strs: list[list[str]] = []
    for prefix, col, suffix in template:
        col_strs.append([f"{prefix}{v}{suffix}" for v in df[col].tolist()])
    return ["".join(row) for row in zip(*col_strs)]


def flow_to_text(row: Mapping[str, object]) -> str:
    """Render one flow record. Byte-identical to reference client1.py:68-81."""
    return render_row(row, _TEMPLATE)


def texts_from_dataframe(df: pd.DataFrame) -> list[str]:
    """CICIDS2017 template over a whole frame (reference client1.py:90)."""
    return render_template(df, _TEMPLATE)


def labels_from_dataframe(
    df: pd.DataFrame, label_column: str = "Label", positive_label: str = "DDoS"
) -> np.ndarray:
    """Binary label map: ``positive_label -> 1 else 0`` (reference client1.py:91)."""
    return (df[label_column] == positive_label).to_numpy().astype(np.int32)
