"""ctypes binding for the native WordPiece batch encoder (wordpiece.so).

Same contract as comm/native.py for the wire byte-path: lazily build + load
the shared library, degrade to the pure-Python implementation when no
toolchain exists. The native path is ASCII-exact with tokenizer.py's
BasicTokenizer+WordPiece (the flow-text templates are pure ASCII); the
wrapper in ``WordPieceTokenizer.batch_encode`` routes non-ASCII batches to
Python, so outputs are identical either way.
"""

from __future__ import annotations

import ctypes
import weakref
from typing import Sequence

import numpy as np

from ..utils.native import load_native


def _configure(cdll: ctypes.CDLL) -> None:
    cdll.wp_create.restype = ctypes.c_void_p
    cdll.wp_create.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    cdll.wp_destroy.argtypes = [ctypes.c_void_p]
    cdll.wp_encode_batch.restype = ctypes.c_int32
    cdll.wp_encode_batch.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int32,
        ctypes.c_int32,
        ctypes.c_int32,
        ctypes.c_void_p,
        ctypes.c_void_p,
    ]


def lib() -> ctypes.CDLL | None:
    return load_native("wordpiece.cpp", "wordpiece.so", _configure)


def have_native() -> bool:
    return lib() is not None


class NativeWordPiece:
    """One vocab bound into the native encoder. ``None``-safe constructor:
    use :func:`NativeWordPiece.create` which returns None when unavailable."""

    def __init__(self, cdll: ctypes.CDLL, handle: int):
        self._cdll = cdll
        self._handle = handle
        self._finalizer = weakref.finalize(self, cdll.wp_destroy, handle)

    @classmethod
    def create(cls, vocab_in_id_order: Sequence[str]) -> "NativeWordPiece | None":
        cdll = lib()
        if cdll is None:
            return None
        blob = "\n".join(vocab_in_id_order).encode("utf-8")
        handle = cdll.wp_create(ctypes.c_char_p(blob), len(blob))
        if not handle:
            return None
        return cls(cdll, handle)

    def encode_batch(
        self, texts: Sequence[str], max_len: int, *, lowercase: bool = True
    ) -> dict[str, np.ndarray] | None:
        """Returns the tokenizer feed dict, or None when any text is
        non-ASCII (caller falls back to Python for exact unicode parity)."""
        n = len(texts)
        input_ids = np.empty((n, max_len), np.int32)
        attention_mask = np.empty((n, max_len), np.int32)
        if n == 0:
            return {"input_ids": input_ids, "attention_mask": attention_mask}
        try:
            encoded = [t.encode("ascii") for t in texts]
        except UnicodeEncodeError:
            return None
        offsets = np.zeros(n + 1, np.int64)
        np.cumsum([len(b) for b in encoded], out=offsets[1:])
        blob = b"".join(encoded)
        rc = self._cdll.wp_encode_batch(
            self._handle,
            ctypes.c_char_p(blob),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n,
            max_len,
            1 if lowercase else 0,
            input_ids.ctypes.data_as(ctypes.c_void_p),
            attention_mask.ctypes.data_as(ctypes.c_void_p),
        )
        if rc != 0:
            return None
        return {"input_ids": input_ids, "attention_mask": attention_mask}
