"""Build the native shared libraries (fedwire.so, wordpiece.so) with g++.

Usage: ``python native/build.py [--out DIR]`` builds everything. Also
importable: ``build(out_dir)`` (fedwire, kept for back-compat) and
``build_lib(src, soname, out_dir)`` return the .so path or None when no
toolchain exists (callers fall back to pure-Python/numpy implementations).
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
DEFAULT_OUT = _HERE
SONAME = "fedwire.so"
LIBS: tuple[tuple[str, str], ...] = (
    ("fedwire.cpp", "fedwire.so"),
    ("wordpiece.cpp", "wordpiece.so"),
)


def build_lib(
    src: str, soname: str, out_dir: str = DEFAULT_OUT, *, force: bool = False
) -> str | None:
    src_path = os.path.join(_HERE, src)
    out = os.path.join(out_dir, soname)
    if (
        not force
        and os.path.exists(out)
        and os.path.getmtime(out) >= os.path.getmtime(src_path)
    ):
        return out
    gxx = shutil.which("g++") or shutil.which("clang++")
    if gxx is None:
        return None
    cmd = [
        gxx,
        "-O3",
        "-shared",
        "-fPIC",
        "-std=c++17",
        "-fno-exceptions",
        src_path,
        "-o",
        out,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except subprocess.CalledProcessError as e:
        sys.stderr.write(f"{soname} build failed:\n{e.stderr}\n")
        return None
    return out


def build(out_dir: str = DEFAULT_OUT, *, force: bool = False) -> str | None:
    """fedwire.so (back-compat entry point used by comm/native.py)."""
    return build_lib("fedwire.cpp", SONAME, out_dir, force=force)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=DEFAULT_OUT)
    ap.add_argument("--force", action="store_true")
    args = ap.parse_args()
    failed = False
    for src, soname in LIBS:
        path = build_lib(src, soname, args.out, force=args.force)
        if path is None:
            failed = True
            sys.stderr.write(f"FAILED: {soname}\n")
        else:
            print(path)
    if failed:
        sys.exit("no C++ toolchain found (g++/clang++) or compile error")
