"""Build fedwire.so (the native wire-format byte-path) with g++.

Usage: ``python native/build.py [--out DIR]``. Also importable:
``build(out_dir)`` returns the .so path or None when no toolchain exists
(callers fall back to the pure-numpy implementations in comm/native.py).
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fedwire.cpp")
DEFAULT_OUT = os.path.dirname(os.path.abspath(__file__))
SONAME = "fedwire.so"


def build(out_dir: str = DEFAULT_OUT, *, force: bool = False) -> str | None:
    out = os.path.join(out_dir, SONAME)
    if not force and os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(_SRC):
        return out
    gxx = shutil.which("g++") or shutil.which("clang++")
    if gxx is None:
        return None
    cmd = [
        gxx,
        "-O3",
        "-shared",
        "-fPIC",
        "-std=c++17",
        "-fno-exceptions",
        _SRC,
        "-o",
        out,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except subprocess.CalledProcessError as e:
        sys.stderr.write(f"fedwire build failed:\n{e.stderr}\n")
        return None
    return out


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=DEFAULT_OUT)
    ap.add_argument("--force", action="store_true")
    args = ap.parse_args()
    path = build(args.out, force=args.force)
    if path is None:
        sys.exit("no C++ toolchain found (g++/clang++)")
    print(path)
