// Native WordPiece batch encoder (ASCII fast path).
//
// The TPU feed format is pre-tokenized [N, max_len] int32 arrays
// (data/pipeline.py); tokenization is the one host-side hot loop left, so it
// gets the same native treatment as the wire byte-path (fedwire.cpp). The
// algorithm mirrors data/tokenizer.py exactly for ASCII input: BERT
// BasicTokenizer (clean -> whitespace split -> lowercase -> punctuation
// split; NFD accent-stripping is a no-op on ASCII) followed by greedy
// longest-match WordPiece with "##" continuations. The Python wrapper
// (data/native_tokenizer.py) routes only pure-ASCII batches here — anything
// else takes the pure-Python path — so parity with the reference HF
// tokenizer behavior (reference client1.py:36-50) is preserved bit-for-bit.
//
// C ABI: wp_create / wp_destroy / wp_encode_batch (see prototypes below).
// Built by native/build.py into wordpiece.so; loaded via ctypes.

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Vocab {
  std::unordered_map<std::string, int32_t> table;
  int32_t pad_id = -1, unk_id = -1, cls_id = -1, sep_id = -1;
  int32_t max_word_chars = 100;
};

inline bool is_ascii_punct(unsigned char c) {
  return (c >= 33 && c <= 47) || (c >= 58 && c <= 64) || (c >= 91 && c <= 96) ||
         (c >= 123 && c <= 126);
}

inline bool is_ws(unsigned char c) {
  return c == ' ' || c == '\t' || c == '\n' || c == '\r';
}

// Greedy longest-match WordPiece of one word -> ids appended to out.
void wordpiece(const Vocab& v, const std::string& word,
               std::vector<int32_t>& out) {
  const size_t n = word.size();
  if (n > static_cast<size_t>(v.max_word_chars)) {
    out.push_back(v.unk_id);
    return;
  }
  const size_t start_len = out.size();
  size_t start = 0;
  std::string probe;
  while (start < n) {
    size_t end = n;
    int32_t piece = -1;
    while (start < end) {
      probe.assign(start > 0 ? "##" : "");
      probe.append(word, start, end - start);
      auto it = v.table.find(probe);
      if (it != v.table.end()) {
        piece = it->second;
        break;
      }
      --end;
    }
    if (piece < 0) {
      out.resize(start_len);
      out.push_back(v.unk_id);
      return;
    }
    out.push_back(piece);
    start = end;
  }
}

// BasicTokenizer (ASCII) + WordPiece over one text -> ids appended to out.
void encode_text(const Vocab& v, const char* s, size_t len, bool lowercase,
                 std::vector<int32_t>& out) {
  std::string word;
  auto flush_word = [&]() {
    if (!word.empty()) {
      wordpiece(v, word, out);
      word.clear();
    }
  };
  for (size_t i = 0; i < len; ++i) {
    unsigned char c = static_cast<unsigned char>(s[i]);
    if (c == 0) continue;                 // cleaned
    if (is_ws(c)) { flush_word(); continue; }
    if (c < 32 || c == 127) continue;     // ASCII control: cleaned
    if (is_ascii_punct(c)) {              // punctuation: standalone token
      flush_word();
      word.push_back(static_cast<char>(c));
      flush_word();
      continue;
    }
    if (lowercase && c >= 'A' && c <= 'Z') c = c - 'A' + 'a';
    word.push_back(static_cast<char>(c));
  }
  flush_word();
}

}  // namespace

extern "C" {

// vocab_blob: '\n'-joined token strings (index = id). Returns handle or null.
void* wp_create(const char* vocab_blob, size_t len) {
  Vocab* v = new (std::nothrow) Vocab();
  if (!v) return nullptr;
  size_t start = 0;
  int32_t id = 0;
  for (size_t i = 0; i <= len; ++i) {
    if (i == len || vocab_blob[i] == '\n') {
      if (i > start) {
        std::string tok(vocab_blob + start, i - start);
        if (tok == "[PAD]") v->pad_id = id;
        else if (tok == "[UNK]") v->unk_id = id;
        else if (tok == "[CLS]") v->cls_id = id;
        else if (tok == "[SEP]") v->sep_id = id;
        v->table.emplace(std::move(tok), id);
        ++id;
      }
      start = i + 1;
    }
  }
  if (v->pad_id < 0 || v->unk_id < 0 || v->cls_id < 0 || v->sep_id < 0) {
    delete v;
    return nullptr;
  }
  return v;
}

void wp_destroy(void* handle) { delete static_cast<Vocab*>(handle); }

// texts_blob + offsets[n_texts+1] (byte offsets into the blob) -> row-major
// out_ids/out_mask [n_texts, max_len], PAD-filled, "[CLS] ... [SEP]" with
// truncation to max_len (specials kept) exactly like tokenizer.py encode().
// Returns 0 on success, -1 on bad arguments.
int wp_encode_batch(void* handle, const char* texts_blob,
                    const int64_t* offsets, int32_t n_texts, int32_t max_len,
                    int32_t lowercase, int32_t* out_ids, int32_t* out_mask) {
  if (!handle || max_len < 2 || n_texts < 0) return -1;
  const Vocab& v = *static_cast<Vocab*>(handle);
  std::vector<int32_t> ids;
  for (int32_t r = 0; r < n_texts; ++r) {
    ids.clear();
    const int64_t b = offsets[r], e = offsets[r + 1];
    if (e < b) return -1;
    encode_text(v, texts_blob + b, static_cast<size_t>(e - b), lowercase != 0,
                ids);
    const int32_t body =
        ids.size() > static_cast<size_t>(max_len - 2) ? max_len - 2
                                                      : static_cast<int32_t>(ids.size());
    int32_t* row_ids = out_ids + static_cast<int64_t>(r) * max_len;
    int32_t* row_mask = out_mask + static_cast<int64_t>(r) * max_len;
    int32_t w = 0;
    row_ids[w++] = v.cls_id;
    for (int32_t i = 0; i < body; ++i) row_ids[w++] = ids[i];
    row_ids[w++] = v.sep_id;
    for (int32_t i = 0; i < w; ++i) row_mask[i] = 1;
    for (int32_t i = w; i < max_len; ++i) {
      row_ids[i] = v.pad_id;
      row_mask[i] = 0;
    }
  }
  return 0;
}

}  // extern "C"
