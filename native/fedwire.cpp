// fedwire: native byte-path for the federated wire format.
//
// The reference ships ~268 MB fp32 state dicts as gzip(pickle(...)) over
// TCP, paying ~11 s of compression per round (reference client1.py:228-234,
// terminal logs). This library replaces that hot byte-path with:
//
//   * crc32           — payload integrity (the reference has no checksum at
//                       all; a flipped bit silently corrupts weights)
//   * pack_bf16 /     — fp32 -> bfloat16 truncation with round-to-nearest-
//     unpack_bf16       even: a 2x payload cut that matches TPU-native
//                       weight precision, instead of byte-level gzip
//   * xor_delta /     — in-place XOR of consecutive round payloads; rounds
//     xor_apply         change few high-order bits, so XOR'd deltas compress
//                       far better if a byte-compressor is layered on top
//
// Built with `python native/build.py` into fedwire.so, loaded via ctypes
// (detecting_cyber..._tpu/comm/native.py) with a numpy fallback when the
// toolchain is unavailable. No Python.h dependency — plain C ABI.

#include <cstddef>
#include <cstdint>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------- crc32
// Slice-by-8 CRC-32 (IEEE 802.3 polynomial, zlib-compatible).
static uint32_t crc_tables[8][256];
static bool crc_init_done = false;

static void crc_init() {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++) c = (c >> 1) ^ (0xEDB88320u & (-(int32_t)(c & 1)));
        crc_tables[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = crc_tables[0][i];
        for (int t = 1; t < 8; t++) {
            c = crc_tables[0][c & 0xFF] ^ (c >> 8);
            crc_tables[t][i] = c;
        }
    }
    crc_init_done = true;
}

uint32_t fedwire_crc32(const uint8_t* data, size_t n, uint32_t seed) {
    if (!crc_init_done) crc_init();
    uint32_t c = ~seed;
    // Process 8 bytes per step.
    while (n >= 8) {
        uint32_t lo, hi;
        std::memcpy(&lo, data, 4);
        std::memcpy(&hi, data + 4, 4);
        lo ^= c;
        c = crc_tables[7][lo & 0xFF] ^ crc_tables[6][(lo >> 8) & 0xFF] ^
            crc_tables[5][(lo >> 16) & 0xFF] ^ crc_tables[4][lo >> 24] ^
            crc_tables[3][hi & 0xFF] ^ crc_tables[2][(hi >> 8) & 0xFF] ^
            crc_tables[1][(hi >> 16) & 0xFF] ^ crc_tables[0][hi >> 24];
        data += 8;
        n -= 8;
    }
    while (n--) c = crc_tables[0][(c ^ *data++) & 0xFF] ^ (c >> 8);
    return ~c;
}

// ------------------------------------------------------------- bf16 pack
// fp32 -> bf16 with round-to-nearest-even (matches TPU hardware rounding).
void fedwire_pack_bf16(const uint32_t* src, uint16_t* dst, size_t n) {
    for (size_t i = 0; i < n; i++) {
        uint32_t x = src[i];
        // NaN must stay NaN: rounding could carry into the exponent and
        // produce inf; force the quiet bit instead.
        if ((x & 0x7FFFFFFFu) > 0x7F800000u) {
            dst[i] = (uint16_t)((x >> 16) | 0x0040u);
            continue;
        }
        uint32_t rounding = 0x7FFFu + ((x >> 16) & 1u);
        dst[i] = (uint16_t)((x + rounding) >> 16);
    }
}

void fedwire_unpack_bf16(const uint16_t* src, uint32_t* dst, size_t n) {
    for (size_t i = 0; i < n; i++) dst[i] = ((uint32_t)src[i]) << 16;
}

// ------------------------------------------------------------- xor delta
// dst := dst XOR src, byte-wise (self-inverse: apply == delta).
void fedwire_xor(const uint8_t* src, uint8_t* dst, size_t n) {
    size_t i = 0;
    for (; i + 8 <= n; i += 8) {
        uint64_t a, b;
        std::memcpy(&a, src + i, 8);
        std::memcpy(&b, dst + i, 8);
        b ^= a;
        std::memcpy(dst + i, &b, 8);
    }
    for (; i < n; i++) dst[i] ^= src[i];
}

}  // extern "C"
