"""Headline benchmark: local-training throughput on the flagship model.

Measures the jitted train step on the full DistilBERT-base DDoS classifier
(66 M params) at the reference's own configuration (batch 16, seq 128,
Adam 2e-5 — reference client1.py:27,370,379-380) and reports samples/sec
against the reference's recorded CPU throughput of ~2.5 batch/s = 40
samples/s (client1_terminal_output.txt:7,9,11; BASELINE.md).

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# Keep the noisy platform banner off stdout (the JSON line must be parseable).
os.environ.setdefault("JAX_LOGGING_LEVEL", "ERROR")

import jax  # noqa: E402

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (  # noqa: E402
    ModelConfig,
    TrainConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.engine import (  # noqa: E402
    Trainer,
)

REFERENCE_SAMPLES_PER_SEC = 40.0  # ~2.5 batch/s * bs 16 (BASELINE.md)


def main() -> None:
    batch_size = int(os.environ.get("BENCH_BATCH", "16"))
    steps = int(os.environ.get("BENCH_STEPS", "100"))
    warmup = int(os.environ.get("BENCH_WARMUP", "10"))

    model_cfg = ModelConfig()  # DistilBERT-base, bf16 compute
    trainer = Trainer(model_cfg, TrainConfig())
    state = trainer.init_state(seed=0)

    rng = np.random.default_rng(0)
    L = model_cfg.max_len
    batch = {
        "input_ids": rng.integers(0, model_cfg.vocab_size, (batch_size, L)).astype(
            np.int32
        ),
        "attention_mask": np.ones((batch_size, L), np.int32),
        "labels": rng.integers(0, 2, batch_size).astype(np.int32),
    }
    batch = {k: jax.device_put(v) for k, v in batch.items()}

    for _ in range(warmup):
        state, loss = trainer.train_step(state, batch)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        state, loss = trainer.train_step(state, batch)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    samples_per_sec = batch_size * steps / dt
    print(
        json.dumps(
            {
                "metric": "train_samples_per_sec_distilbert_bs%d" % batch_size,
                "value": round(samples_per_sec, 2),
                "unit": "samples/sec",
                "vs_baseline": round(samples_per_sec / REFERENCE_SAMPLES_PER_SEC, 2),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
